"""MTP speculative decoding: draft-and-verify with variable tokens/step.

The round-12 tentpole: an MTP-style drafter proposes K tokens, one fused
target-model forward verifies all K positions (spending decode's idle
MXU FLOPs on the weight stream it already pays for), on-device
accept/reject + bonus sampling emits 1..K+1 tokens per engine step, and
rejected drafts' KV blocks roll back to the pool the same step.

The correctness contract this suite pins (fail-fast in ci-gate):

  - spec output is BYTE-IDENTICAL to non-spec decode for greedy and
    seeded sampling (``fold_in(seed, gen_idx)`` continuity), whatever
    the drafter proposes — drafter quality moves throughput only;
  - rejection rollback leaves the paged-KV pool leak-free and the
    prefix cache consistent across block boundaries (PR 9's
    restore-or-recompute resume lands on a clean prefix);
  - adaptive K backs off to 1 when measured acceptance is low;
  - ``LLMD_SPEC_DECODE=off`` / ``LLMD_SPEC_K=0`` is today's engine;
  - chaos acceptance: a seeded mid-stream engine kill during spec
    decode resumes through PR 9's journaled failover with ZERO client
    breaks and exact multi-token journal offsets;
  - JIT meta-gate: the spec path adds no host sync beyond its one
    documented batched fetch.

All CPU, tier-1 safe.
"""

import asyncio
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_d_tpu.analysis.core import Context, run_passes
from llm_d_tpu.analysis.passes.jit_hygiene import JitHygienePass
from llm_d_tpu.engine.engine import EngineConfig, EngineCore
from llm_d_tpu.engine.request import Request
from llm_d_tpu.models import get_config, get_model
from llm_d_tpu.ops import sampling as sampling_ops
from llm_d_tpu.ops.sampling import SamplingParams
from llm_d_tpu.predictor.model import SpecAcceptanceTracker
from llm_d_tpu.sim.simulator import SimConfig, build_sim_server
from llm_d_tpu.server.stream_resume import (
    parse_stream_payload,
    verify_continuity,
)
from llm_d_tpu.utils import tracing

REPO = pathlib.Path(__file__).resolve().parent.parent

ENGINE_KW = dict(model="tiny", block_size=4, num_blocks=64, max_num_seqs=8,
                 max_num_batched_tokens=64, min_token_bucket=16,
                 min_seq_bucket=4)


def greedy_req(rid, prompt, n=12, **kw):
    return Request(request_id=rid, prompt_token_ids=list(prompt),
                   sampling=SamplingParams(temperature=0.0, max_tokens=n,
                                           ignore_eos=True), **kw)


def seeded_req(rid, prompt, n=12, seed=7, **kw):
    return Request(request_id=rid, prompt_token_ids=list(prompt),
                   sampling=SamplingParams(temperature=0.9, top_p=0.95,
                                           top_k=20, max_tokens=n,
                                           seed=seed, ignore_eos=True),
                   **kw)


def _free_blocks(engine):
    return engine.kv_manager.num_free_blocks


# ---------------------------------------------------------------------------
# units: on-device verifier, drafter, acceptance tracker
# ---------------------------------------------------------------------------

def test_spec_verify_greedy_prefix_acceptance():
    """Greedy verification: acceptance is the longest prefix where the
    drafts equal the target argmax, bounded by each row's live-draft
    count; emitted ids are the target's own samples at every position."""
    S, K, V = 2, 3, 8
    Q = K + 1
    logits = np.full((S * Q, V), -10.0, np.float32)
    # Row 0 target argmax sequence: 5, 2, 7, 1.
    for q, t in enumerate([5, 2, 7, 1]):
        logits[q, t] = 10.0
    # Row 1 target argmax sequence: 3, 3, 3, 3.
    for q in range(Q):
        logits[Q + q, 3] = 10.0
    ids, accepted = sampling_ops.spec_verify(
        jnp.asarray(logits),
        jnp.asarray([[5, 2, 0],           # matches 2 then diverges
                     [3, 3, 3]]),         # matches all 3
        jnp.asarray([3, 2]),              # row 1 only has 2 live drafts
        jnp.zeros(S), jnp.zeros(S, jnp.int32), jnp.ones(S),
        jax.random.PRNGKey(0), seeds=jnp.full(S, -1, jnp.int32),
        gen0=jnp.zeros(S, jnp.int32))
    assert list(np.asarray(accepted)) == [2, 2]
    assert list(np.asarray(ids)[0]) == [5, 2, 7, 1]
    assert list(np.asarray(ids)[1]) == [3, 3, 3, 3]


def test_spec_verify_seeded_rows_match_sample_contract():
    """Seeded rows draw exactly what ``sample`` draws at the same
    (seed, gen_idx) — the fold_in continuity that makes spec output
    byte-identical to single-step seeded decode."""
    S, K, V = 1, 2, 32
    Q = K + 1
    key = jax.random.PRNGKey(9)
    logits = jax.random.normal(key, (S * Q, V), jnp.float32) * 3
    seeds = jnp.asarray([123], jnp.int32)
    gen0 = jnp.asarray([5], jnp.int32)
    temp = jnp.asarray([0.8])
    ids, _ = sampling_ops.spec_verify(
        logits, jnp.zeros((S, K), jnp.int32), jnp.zeros(S, jnp.int32),
        temp, jnp.zeros(S, jnp.int32), jnp.ones(S), key,
        seeds=seeds, gen0=gen0)
    for q in range(Q):
        want = sampling_ops.sample(
            logits[q][None], temp, jnp.zeros(1, jnp.int32), jnp.ones(1),
            jax.random.PRNGKey(q + 77),       # step key must not matter
            seeds=seeds, gen_idx=gen0 + q)
        assert int(np.asarray(ids)[0, q]) == int(want[0])


def test_drafter_shapes_and_determinism():
    c = get_config("tiny")
    model = get_model(c)
    params = model.init_params(c, jax.random.PRNGKey(0))
    dparams = model.init_draft_params(c, jax.random.PRNGKey(1))
    hidden = jax.random.normal(jax.random.PRNGKey(2), (3, c.hidden_size),
                               c.jax_dtype)
    last = jnp.asarray([1, 2, 3], jnp.int32)
    d1 = model.draft_propose(params, dparams, hidden, last, 4, c)
    d2 = model.draft_propose(params, dparams, hidden, last, 4, c)
    assert d1.shape == (3, 4)
    assert (np.asarray(d1) == np.asarray(d2)).all()
    assert ((np.asarray(d1) >= 0) & (np.asarray(d1) < c.vocab_size)).all()


def test_moe_model_exposes_drafter():
    from llm_d_tpu.models import moe
    assert hasattr(moe, "init_draft_params")
    assert hasattr(moe, "draft_propose")


def test_acceptance_tracker_backoff_and_recovery():
    tr = SpecAcceptanceTracker(k_max=4, low=0.35, alpha=0.5)
    assert tr.suggest_k("r") == 4            # optimistic start
    for _ in range(6):
        tr.observe("r", 4, 0)                # nothing accepted
    assert tr.suggest_k("r") == 1            # backed off
    for _ in range(8):
        tr.observe("r", 1, 1)                # K=1 keeps measuring
    assert tr.suggest_k("r") == 4            # recovered
    tr.forget("r")
    assert tr.rate("r") is None


def test_acceptance_tracker_table_is_bounded():
    tr = SpecAcceptanceTracker(k_max=4, cap=8)
    for i in range(50):
        tr.observe(f"r{i}", 4, 2)
    assert len(tr._rate) <= 8


# ---------------------------------------------------------------------------
# engine: byte-identical parity, rollback, prefix-cache integrity
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def plain_engine():
    return EngineCore(EngineConfig(**ENGINE_KW))


# Shared spec engines (module scope): every EngineCore compiles its own
# program set, so tests reuse two instances — one with the REAL verifier
# (byte-parity tests) and one with the seeded fixed-accept coin
# (multi-token-step mechanics).  Identical config seed 0 => identical
# params across all tiny engines in this file, so parity comparisons
# against plain_engine are exact.
@pytest.fixture(scope="module")
def spec_engine():
    eng = EngineCore(EngineConfig(spec_k=4, **ENGINE_KW))
    assert eng.spec_k == 4
    return eng


@pytest.fixture(scope="module")
def fixed_engine():
    return EngineCore(EngineConfig(spec_k=4, spec_fixed_accept=0.8,
                                   **ENGINE_KW))


def test_spec_greedy_byte_identical_parity(plain_engine, spec_engine):
    """Across block boundaries (block_size 4, 12 output tokens) the spec
    engine's greedy output matches the plain engine token for token —
    the drafter is random-init (near-zero acceptance) and it still
    cannot perturb output, only throughput."""
    prompts = {"a": [1, 5, 9, 200, 3, 17, 42], "b": [4, 4, 4, 8],
               "c": list(range(40, 55))}
    want = plain_engine.generate(
        [greedy_req(r, p) for r, p in prompts.items()])
    got = spec_engine.generate(
        [greedy_req(r, p) for r, p in prompts.items()])
    assert got == want


def test_spec_seeded_byte_identical_parity(plain_engine, spec_engine):
    reqs = lambda: [seeded_req("sa", [1, 5, 9, 200, 3], seed=7),  # noqa: E731
                    seeded_req("sb", [4, 4, 4, 8], seed=99)]
    want = plain_engine.generate(reqs())
    got = spec_engine.generate(reqs())
    assert got == want


def test_spec_fixed_accept_emits_multi_token_steps(fixed_engine):
    """The bench harness's seeded-acceptance mode: accepted runs really
    are multi-token (the variable tokens-per-step machinery engages) and
    the per-request draft/accept bookkeeping records them."""
    reqs = [greedy_req(f"fr{i}", [3 * i + 1, 2, 9], n=24)
            for i in range(3)]
    out = fixed_engine.generate(reqs)
    assert all(len(v) == 24 for v in out.values())
    drafted = sum(r.spec_drafted for r in reqs)
    accepted = sum(r.spec_accepted for r in reqs)
    assert drafted > 0 and accepted > 0
    m = fixed_engine.metrics.render().decode()
    assert 'llmd_tpu:spec_draft_tokens_total{model_name="tiny"}' in m
    assert 'llmd_tpu:spec_accepted_tokens_total{model_name="tiny"}' in m


def test_spec_rollback_leaves_pool_leak_free(fixed_engine):
    """After every request finishes, every block is back in the pool and
    no refcounts linger — the rejection rollback (kv_cache.trim_request)
    settled each step's speculative over-allocation."""
    free0 = _free_blocks(fixed_engine)
    reqs = [greedy_req(f"lk{i}", [i + 1, 7, 9, 2, 5], n=13)
            for i in range(5)]
    fixed_engine.generate(reqs)
    assert _free_blocks(fixed_engine) == free0
    assert fixed_engine.kv_manager._ref == {}
    assert all(r.block_ids == [] for r in reqs)


def test_spec_midstream_pool_never_holds_rejected_tail(fixed_engine):
    """DURING decode the pool never holds more than the accepted content
    plus the pending token's slot per request — stepping manually and
    checking after each step that block counts never exceed
    ceil(num_tokens / block_size), i.e. the up-to-K+1-token speculative
    allocation's rejected tail went back the same step."""
    req = greedy_req("mid", [1, 2, 3], n=20)
    fixed_engine.add_request(req)
    bs = fixed_engine.config.block_size
    while fixed_engine.has_work():
        fixed_engine.step()
        if req.state.value == "running":
            assert len(req.block_ids) <= -(-req.num_tokens // bs)
            assert len(req.block_ids) >= \
                -(-req.num_computed_tokens // bs)


def _generate_with_oracle_drafts(spec, req, want, K=4):
    """Drive a spec engine feeding the KNOWN-correct future tokens as
    drafts (the greedy oracle sequence), so the REAL verifier accepts at
    full depth — multi-token accepted runs with byte-identical output,
    no fixed-accept shortcut."""
    spec.add_request(req)
    while spec.has_work():
        j = len(req.output_token_ids)
        if (req.state.value == "running"
                and req.num_computed_tokens == req.num_tokens - 1
                and j < len(want)):
            req.spec_drafts = list(want[j:j + K])
            req.spec_drafts_at = req.num_tokens
        spec.step()
    return list(req.output_token_ids)


def test_spec_oracle_drafts_full_acceptance_parity(plain_engine,
                                                   spec_engine):
    """With a perfect drafter the REAL verifier accepts whole runs
    (multi-token steps, no fixed-accept shortcut) and output stays
    byte-identical — acceptance moved throughput, not content."""
    prompt = [2, 5, 9, 201, 3, 17, 42]
    want = plain_engine.generate([greedy_req("ow", prompt, 12)])["ow"]
    req = greedy_req("o", prompt, 12)
    got = _generate_with_oracle_drafts(spec_engine, req, want)
    assert got == want
    assert req.spec_accepted > 0, "oracle drafts were not accepted"
    assert req.spec_accepted == req.spec_drafted   # all of them, in fact


def test_spec_prefix_cache_consistent_across_block_boundaries(
        plain_engine, spec_engine):
    """The prefix cache after a spec run indexes ONLY accepted content:
    a second request sharing the first's full (prompt + generated)
    prefix — the PR 9 resume admission shape — restores through the
    generated region and continues byte-identically.  The first run
    uses oracle drafts so accepted multi-token runs really crossed
    block boundaries (block_size 4 vs up-to-5-token steps)."""
    prompt = [9, 8, 7, 6, 5, 4, 3, 2, 1, 9, 8, 7]
    want = plain_engine.generate([greedy_req("pw", prompt, 12)])["pw"]
    r1 = greedy_req("first", prompt, 12)
    out = _generate_with_oracle_drafts(spec_engine, r1, want)
    assert out == want
    assert r1.spec_accepted > 0
    # Fresh same-prompt request: hits the cached prompt blocks.
    r2 = greedy_req("second", prompt, 12)
    out2 = spec_engine.generate([r2])["second"]
    assert out2 == out
    assert r2.num_cached_prompt_tokens >= 8
    # Resume shape: output pre-populated from a journal, restore-first
    # through the GENERATED region the spec run cached.
    r3 = greedy_req("resume", prompt, 12)
    r3.output_token_ids = list(out[:6])
    r3.resume_offset = 6
    got = spec_engine.generate([r3])["resume"]
    assert got[6:] == out[6:]
    assert r3.resume_restored_tokens >= 0   # restored or recomputed: clean


def test_spec_adaptive_k_backs_off_on_rejection():
    """spec_fixed_accept=0.0 rejects every draft: after a few steps the
    tracker pins the request at K=1 and the scheduler stops paying for
    depth-4 verification."""
    spec = EngineCore(EngineConfig(spec_k=4, spec_fixed_accept=0.0,
                                   **ENGINE_KW))
    req = greedy_req("r", [1, 2, 3], n=16)
    out = spec.generate([req])["r"]
    assert len(out) == 16                   # still correct, one tok/step
    # The tracker state is dropped at finish (leak-free); back off is
    # observable mid-run via the lookahead the last steps actually used.
    assert req.spec_drafted < 4 * 15        # not every step paid depth 4


def test_spec_mixed_round_runs_fused_with_correct_output(plain_engine,
                                                         spec_engine):
    """A prefill admitted mid-decode rides the SAME fused program as the
    decode/verify rows (round 15: no classic fallback, no draft
    rollback) — both requests finish with byte-correct output and the
    pool is leak-free afterwards."""
    free0 = _free_blocks(spec_engine)
    a = greedy_req("ma", [1, 5, 9, 200, 3], n=14)
    b = greedy_req("mb", [4, 4, 4, 8], n=10)
    spec_engine.add_request(a)
    for _ in range(4):                      # let a reach spec decode
        spec_engine.step()
    spec_engine.add_request(b)              # forces mixed rounds
    while spec_engine.has_work():
        spec_engine.step()
    assert _free_blocks(spec_engine) == free0
    # Parity vs a plain engine run with the same staggering-free inputs:
    # greedy output depends only on the prefix, so solo runs are the
    # oracle for both.
    want_a = plain_engine.generate(
        [greedy_req("ma2", [1, 5, 9, 200, 3], 14)])["ma2"]
    want_b = plain_engine.generate(
        [greedy_req("mb2", [4, 4, 4, 8], 10)])["mb2"]
    assert a.output_token_ids == want_a
    assert b.output_token_ids == want_b


def test_spec_respects_max_tokens_and_model_len():
    """max_tokens not a multiple of the emitted chunk sizes: the engine
    never over-emits, and the lookahead never drafts past the request's
    own budget."""
    spec = EngineCore(EngineConfig(spec_k=4, spec_fixed_accept=1.0,
                                   **ENGINE_KW))
    for n in (1, 2, 5, 7):
        out = spec.generate([greedy_req(f"n{n}", [1, 2, 3], n)])
        assert len(out[f"n{n}"]) == n


# ---------------------------------------------------------------------------
# knobs: env resolution, kill switch, flag
# ---------------------------------------------------------------------------

def test_env_off_is_todays_engine(monkeypatch, plain_engine):
    monkeypatch.setenv("LLMD_SPEC_DECODE", "off")
    eng = EngineCore(EngineConfig(spec_k=4, **ENGINE_KW))
    assert eng.spec_k == 0 and eng._spec_fn is None
    assert eng.scheduler.spec_lookahead is None
    out = eng.generate([greedy_req("a", [1, 5, 9, 200, 3])])
    want = plain_engine.generate([greedy_req("a", [1, 5, 9, 200, 3])])
    assert out == want


def test_env_k_resolution_and_invalid_fallback(monkeypatch):
    monkeypatch.setenv("LLMD_SPEC_K", "3")
    eng = EngineCore(EngineConfig(**ENGINE_KW))
    assert eng.spec_k == 3
    monkeypatch.setenv("LLMD_SPEC_K", "banana")    # env_int fallback -> 0
    eng = EngineCore(EngineConfig(**ENGINE_KW))
    assert eng.spec_k == 0


def test_default_engine_has_spec_off():
    eng = EngineCore(EngineConfig(**ENGINE_KW))
    assert eng.spec_k == 0 and eng._spec_fn is None


def test_spec_stays_on_under_multistep_and_async():
    # Round 16: the composition gate is gone — spec decode IS the body
    # of the fused-multistep pipeline, so requesting both keeps both.
    eng = EngineCore(EngineConfig(spec_k=4, num_scheduler_steps=4,
                                  async_scheduling=True, **ENGINE_KW))
    assert eng.spec_k == 4 and eng._spec_fn is not None


def test_server_flag_threads_spec_k():
    from llm_d_tpu.server.openai import (
        build_arg_parser, engine_config_from_args)
    p = build_arg_parser()
    cfg = engine_config_from_args(p.parse_args(["--spec-k", "4"]))
    assert cfg.spec_k == 4
    cfg = engine_config_from_args(p.parse_args([]))
    assert cfg.spec_k is None               # defer to LLMD_SPEC_K


# ---------------------------------------------------------------------------
# observability: step spans carry drafted/accepted
# ---------------------------------------------------------------------------

def test_engine_step_spans_carry_spec_attrs(fixed_engine):
    root = tracing.get_tracer("server").start_span(
        "server.request", request_id="req-spec", criticality="standard")
    req = greedy_req("traced", [1, 2, 3, 4, 5], n=12)
    req.trace_ctx = root.ctx()
    fixed_engine.generate([req])
    root.end()
    steps = [s for s in tracing.get_tracer("engine").snapshot()
             if s["name"] == "engine.step"
             and s.get("attrs", {}).get("spec")]
    assert steps, "no spec engine.step spans recorded"
    assert any(s["attrs"].get("drafted", 0) > 0 for s in steps)
    assert all("accepted" in s["attrs"] for s in steps)


def test_jit_meta_gate_spec_adds_no_host_sync():
    """The spec path's only sync is its one documented batched fetch
    (ids + accepted counts + next drafts): the JIT hygiene pass stays
    green and the suppressed deliberate sync points now number three."""
    ctx = Context(REPO)
    findings, suppressed, _ = run_passes(ctx, [JitHygienePass()])
    assert findings == [], "\n".join(f.render() for f in findings)
    assert suppressed >= 3


# ---------------------------------------------------------------------------
# sim mirror + chaos acceptance: PR 9 resume during spec decode
# ---------------------------------------------------------------------------

def _sim_text(sim, prompt, max_tokens):
    from llm_d_tpu.sim.simulator import _LOREM
    pids = sim._tokenize(prompt)
    return "".join(_LOREM[(len(pids) + i) % len(_LOREM)] + " "
                   for i in range(max_tokens))


def test_sim_spec_mirror_multi_token_chunks():
    """The sim's seeded acceptance model emits multi-token SSE frames
    with exact offsets — same text as a non-spec sim, clean continuity,
    spec metrics exported."""
    from test_stream_recovery import _cleanup, _start_app, free_port
    import aiohttp

    async def run():
        port = free_port()
        srv = build_sim_server(SimConfig(ttft_ms=1.0, tpot_ms=1.0,
                                         spec_k=4, spec_acceptance=0.8))
        runner = await _start_app(srv.build_app(), port)
        try:
            async with aiohttp.ClientSession() as sess:
                for _ in range(100):
                    async with sess.get(
                            f"http://127.0.0.1:{port}/v1/models") as r:
                        if r.status == 200:
                            break
                    await asyncio.sleep(0.02)
                async with sess.post(
                        f"http://127.0.0.1:{port}/v1/completions",
                        json={"prompt": "spec sim smoke", "max_tokens": 10,
                              "stream": True}) as r:
                    assert r.status == 200
                    payload = await r.read()
                async with sess.get(
                        f"http://127.0.0.1:{port}/metrics") as r:
                    mtext = await r.text()
        finally:
            await _cleanup([runner])
        text, metas, done = parse_stream_payload(payload)
        assert done
        assert verify_continuity(metas, expect_total=10) == []
        assert max(len(m["tok"]) for m in metas) > 1
        assert text == _sim_text(srv.sim, "spec sim smoke", 10)
        assert "llmd_tpu:spec_draft_tokens_total" in mtext

    asyncio.run(asyncio.wait_for(run(), timeout=60))


def test_chaos_spec_decode_resume_zero_stream_breaks(inject=None):
    """THE chaos acceptance bar for round 12: a 4-replica SPEC-mode sim
    fleet behind the gateway under streaming load; a seeded mid-stream
    ``engine.step`` kill.  Multi-token chunks make journal offsets
    coarser — the resume must still splice at EXACT offsets: zero
    client-visible breaks, zero duplicate/missing token indices,
    byte-identical text, recovery recorded."""
    import aiohttp
    from test_stream_recovery import (
        _cleanup, _metric_value, _start_app, free_port)
    from llm_d_tpu.epp.datastore import EndpointState
    from llm_d_tpu.epp.service import build_gateway
    from llm_d_tpu.utils.faultinject import FaultInjector, install, reset

    inj = install(FaultInjector.from_spec("", seed=0))
    inj.add_rule("engine.step", after=25, count=1)

    async def run():
        ports = [free_port() for _ in range(4)]
        runners, sims = [], []
        for i, port in enumerate(ports):
            srv = build_sim_server(SimConfig(
                model=f"sim-{i}", ttft_ms=1.0, tpot_ms=2.0,
                spec_k=4, spec_acceptance=0.8))
            sims.append(srv.sim)
            runners.append(await _start_app(srv.build_app(), port))
        endpoints = [EndpointState(address=f"127.0.0.1:{p}")
                     for p in ports]
        gw = build_gateway(endpoints, scrape_interval_s=0.05,
                           retry_attempts=3)
        gw_port = free_port()
        gw_runner = await _start_app(gw.build_app(), gw_port)
        url = f"http://127.0.0.1:{gw_port}/v1/completions"
        for _ in range(200):
            if all(e.ready for e in gw.datastore.candidates()):
                break
            await asyncio.sleep(0.02)

        max_tokens = 8
        results = []
        stop = asyncio.Event()

        async def load_worker(sess, wid):
            i = 0
            while not stop.is_set():
                i += 1
                prompt = f"spec chaos {wid} {i} tail"
                try:
                    async with sess.post(url, json={
                            "prompt": prompt, "max_tokens": max_tokens,
                            "stream": True}) as r:
                        payload = await r.read()
                        text, metas, done = parse_stream_payload(payload)
                        results.append(
                            (prompt, r.status, text, metas, done))
                except aiohttp.ClientError as e:
                    results.append((prompt, f"error:{type(e).__name__}",
                                    "", [], False))
                await asyncio.sleep(0.005)

        try:
            async with aiohttp.ClientSession(
                    timeout=aiohttp.ClientTimeout(total=30)) as sess:
                workers = [asyncio.create_task(load_worker(sess, w))
                           for w in range(3)]
                for _ in range(600):
                    await asyncio.sleep(0.02)
                    if inj.stats().get("engine.step", {}).get(
                            "fired", 0) >= 1 and len(results) > 25:
                        break
                await asyncio.sleep(0.3)
                stop.set()
                await asyncio.gather(*workers, return_exceptions=True)
        finally:
            mtext = gw.scheduler.metrics.render().decode()
            await _cleanup(runners + [gw_runner])

        assert inj.stats()["engine.step"]["fired"] >= 1
        assert any(s.dead for s in sims), "no sim died"
        bad = [(p, s) for p, s, *_ in results if s != 200]
        assert not bad, f"client-visible failures: {bad[:5]}"
        breaks = [p for p, _s, _t, _m, done in results if not done]
        assert not breaks, f"{len(breaks)} stream break(s): {breaks[:3]}"
        saw_multi = False
        for prompt, _s, text, metas, _d in results:
            assert verify_continuity(metas, expect_total=max_tokens) \
                == [], prompt
            assert text == _sim_text(sims[0], prompt, max_tokens), \
                f"token sequence diverged for {prompt!r}"
            saw_multi |= any(len(m.get("tok") or []) > 1 for m in metas)
        assert saw_multi, "no multi-token spec chunk observed under load"
        assert _metric_value(
            mtext, "llmd_tpu:stream_resume_total") >= 1.0
        assert _metric_value(
            mtext, 'llmd_tpu:stream_resume_total{outcome="failed"}') \
            == 0.0

    try:
        asyncio.run(asyncio.wait_for(run(), timeout=120))
    finally:
        reset()


# ---------------------------------------------------------------------------
# bench wiring: gated metric + per-K table helpers
# ---------------------------------------------------------------------------

def test_bench_gate_includes_spec_metric():
    import bench
    gate = bench._regression_gate(
        {}, {}, None,
        {256: {"decode_tok_s": 123.0, "decode_tok_s_band": [120.0, 125.0]}})
    assert gate["moe_decode_spec_bs256_best_recorded"] is None
    assert gate["moe_decode_spec_bs256_recorded"] == 123.0
    assert gate["moe_decode_spec_bs256_regressed"] is None   # first record
    # No spec sweep (e.g. --quick): the metric degrades to no-verdict.
    gate = bench._regression_gate({}, {}, None, None)
    assert gate["moe_decode_spec_bs256_delta_pct"] is None


@pytest.mark.slow
def test_bench_spec_accepted_tok_s_on_tiny():
    import bench
    out = bench.bench_spec("tiny", 4, 2, 0.7, prompt_len=8,
                           decode_steps=8)
    row = out[4]
    assert row["decode_tok_s"] > 0
    assert 0 <= row["spec_acceptance_pct"] <= 100
    assert row["accepted_tokens_per_step"] >= 1.0
