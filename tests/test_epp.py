"""EPP scheduler: config parsing, plugin scoring, profile handling, and the
e2e multi-replica routing contract (prefix-affine requests land on the warm
replica via x-gateway-destination-endpoint) against simulator backends.

Reference behavior being mirrored: gaie values plugin configs (SURVEY.md
§2.4), EPP decision header (standalone values.yaml:170-181), KV-event-fed
precise prefix scoring (gaie-kv-events/values.yaml:42-70).
"""

import asyncio
import socket

import pytest

from llm_d_tpu.epp.config import DEFAULT_CONFIG_YAML, parse_config
from llm_d_tpu.epp.datastore import Datastore, EndpointState
from llm_d_tpu.epp.indexer import PrefixIndex
from llm_d_tpu.epp.plugins import (
    KvCacheUtilizationScorer,
    PdProfileHandler,
    PrefixCacheScorer,
    QueueScorer,
    RequestCtx,
)
from llm_d_tpu.epp.scheduler import DESTINATION_HEADER, EppScheduler
from llm_d_tpu.utils.metrics import EppMetrics


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def make_datastore(n=3, role="both"):
    eps = [EndpointState(address=f"10.0.0.{i}:8200", role=role,
                         ready=True) for i in range(n)]
    return Datastore(eps, scrape_interval_s=999)


# ---------- config ----------

def test_parse_default_config():
    cfg = parse_config(DEFAULT_CONFIG_YAML)
    types = {p.type for p in cfg.plugins}
    assert "queue-scorer" in types and "max-score-picker" in types
    prof = cfg.profile("default")
    weights = {r.plugin_ref: r.weight for r in prof.plugins}
    assert weights["prefix-cache-scorer"] == 3.0
    assert weights["queue-scorer"] == 2.0


def test_parse_named_plugin_instances():
    cfg = parse_config("""
kind: EndpointPickerConfig
plugins:
- type: prefix-cache-scorer
  name: gpu-prefix-scorer
  parameters: {lruCapacityPerServer: 100}
- type: prefix-cache-scorer
  name: cpu-prefix-scorer
  parameters: {lruCapacityPerServer: 41000}
- type: max-score-picker
schedulingProfiles:
- name: default
  plugins:
  - pluginRef: gpu-prefix-scorer
    weight: 2
  - pluginRef: cpu-prefix-scorer
    weight: 1
  - pluginRef: max-score-picker
""")
    assert cfg.plugin("gpu-prefix-scorer").parameters[
        "lruCapacityPerServer"] == 100
    assert cfg.plugin("cpu-prefix-scorer").parameters[
        "lruCapacityPerServer"] == 41000


# ---------- scorers ----------

def test_queue_scorer_prefers_empty_queue():
    ds = make_datastore()
    eps = ds.candidates()
    eps[0].num_waiting = 10
    eps[1].num_waiting = 0
    eps[2].num_waiting = 5
    scores = QueueScorer("q", {}, ds).score(RequestCtx(body={}), eps)
    assert scores[eps[1].address] == 1.0
    assert scores[eps[0].address] == 0.0


def test_kv_util_scorer():
    ds = make_datastore()
    eps = ds.candidates()
    eps[0].kv_usage = 0.9
    eps[1].kv_usage = 0.1
    scores = KvCacheUtilizationScorer("kv", {}, ds).score(
        RequestCtx(body={}), eps)
    assert scores[eps[1].address] > scores[eps[0].address]
    assert abs(scores[eps[1].address] - 0.9) < 1e-9


def test_prefix_scorer_learns_routing():
    ds = make_datastore()
    eps = ds.candidates()
    sc = PrefixCacheScorer("p", {"hashBlockSize": 4}, ds)
    ctx = RequestCtx(body={}, token_ids=list(range(16)))
    assert all(v == 0.0 for v in sc.score(ctx, eps).values())
    sc.on_picked(ctx, eps[1], "default")
    scores = sc.score(ctx, eps)
    assert scores[eps[1].address] == 1.0
    assert scores[eps[0].address] == 0.0
    # Shared 8-token prefix -> half the blocks match.
    ctx2 = RequestCtx(body={}, token_ids=list(range(8)) + [99] * 8)
    assert sc.score(ctx2, eps)[eps[1].address] == pytest.approx(0.5)


def test_precise_prefix_index_and_scorer():
    from llm_d_tpu.epp.plugins import PrecisePrefixCacheScorer
    idx = PrefixIndex()
    ds = make_datastore()
    eps = ds.candidates()
    ctx = RequestCtx(body={}, token_ids=list(range(128)))
    keys = ctx.block_keys(64)
    idx.on_event(eps[2].address, "BlockStored", keys)
    sc = PrecisePrefixCacheScorer("pp", {"blockSize": 64}, ds, indexer=idx)
    scores = sc.score(ctx, eps)
    assert scores[eps[2].address] == 1.0
    assert scores[eps[0].address] == 0.0
    # Removal drops residency.
    idx.on_event(eps[2].address, "BlockRemoved", keys)
    assert sc.score(ctx, eps)[eps[2].address] == 0.0


# ---------- profiles / scheduler ----------

def test_pd_profile_handler_threshold():
    ds = make_datastore()
    h = PdProfileHandler("pd", {"threshold": 10}, ds, metrics=EppMetrics())
    short = RequestCtx(body={}, token_ids=[1] * 5)
    long = RequestCtx(body={}, token_ids=[1] * 50)
    assert h.profiles(short, ["prefill", "decode"]) == ["decode"]
    assert h.profiles(long, ["prefill", "decode"]) == ["prefill", "decode"]


def test_scheduler_picks_least_loaded():
    cfg = parse_config("""
kind: EndpointPickerConfig
plugins:
- type: single-profile-handler
- type: queue-scorer
- type: kv-cache-utilization-scorer
- type: max-score-picker
schedulingProfiles:
- name: default
  plugins:
  - pluginRef: queue-scorer
    weight: 2
  - pluginRef: kv-cache-utilization-scorer
    weight: 2
  - pluginRef: max-score-picker
""")
    ds = make_datastore()
    eps = ds.candidates()
    eps[0].num_waiting, eps[0].kv_usage = 8, 0.8
    eps[1].num_waiting, eps[1].kv_usage = 0, 0.1
    eps[2].num_waiting, eps[2].kv_usage = 4, 0.5
    sched = EppScheduler(cfg, ds)
    result = sched.schedule(RequestCtx(body={}, prompt_text="hello"))
    assert result.primary.address == eps[1].address
    assert result.headers[DESTINATION_HEADER] == eps[1].address


def test_pd_scheduler_sets_prefill_header():
    cfg = parse_config("""
kind: EndpointPickerConfig
plugins:
- type: pd-profile-handler
  parameters: {threshold: 0}
- type: prefill-header-handler
- type: prefill-filter
- type: decode-filter
- type: queue-scorer
- type: max-score-picker
schedulingProfiles:
- name: prefill
  plugins:
  - pluginRef: prefill-filter
  - pluginRef: queue-scorer
  - pluginRef: max-score-picker
- name: decode
  plugins:
  - pluginRef: decode-filter
  - pluginRef: queue-scorer
  - pluginRef: max-score-picker
""")
    eps = [EndpointState(address="10.0.0.1:8000", role="prefill", ready=True),
           EndpointState(address="10.0.0.2:8000", role="decode", ready=True)]
    ds = Datastore(eps, scrape_interval_s=999)
    sched = EppScheduler(cfg, ds)
    result = sched.schedule(RequestCtx(body={}, token_ids=[1] * 64))
    assert result.picks["prefill"].address == "10.0.0.1:8000"
    assert result.picks["decode"].address == "10.0.0.2:8000"
    assert result.primary.address == "10.0.0.2:8000"   # decode serves
    assert result.headers["x-prefiller-host-port"] == "10.0.0.1:8000"
    assert result.headers[DESTINATION_HEADER] == "10.0.0.2:8000"


def test_prefill_header_ranks_alternates():
    """With several prefillers the hint header carries the winner plus
    score-ranked runners-up — the sidecar's failover list (single-
    prefiller pools keep the bare-address wire format)."""
    cfg = parse_config("""
kind: EndpointPickerConfig
plugins:
- type: pd-profile-handler
  parameters: {threshold: 0}
- type: prefill-header-handler
- type: prefill-filter
- type: decode-filter
- type: queue-scorer
- type: max-score-picker
schedulingProfiles:
- name: prefill
  plugins:
  - pluginRef: prefill-filter
  - pluginRef: queue-scorer
  - pluginRef: max-score-picker
- name: decode
  plugins:
  - pluginRef: decode-filter
  - pluginRef: queue-scorer
  - pluginRef: max-score-picker
""")
    eps = [EndpointState(address=f"10.0.0.{i}:8000", role="prefill",
                         ready=True) for i in range(3)]
    eps.append(EndpointState(address="10.0.0.9:8000", role="decode",
                             ready=True))
    eps[0].num_waiting, eps[1].num_waiting, eps[2].num_waiting = 5, 0, 2
    ds = Datastore(eps, scrape_interval_s=999)
    sched = EppScheduler(cfg, ds)
    result = sched.schedule(RequestCtx(body={}, token_ids=[1] * 64))
    ranked = result.headers["x-prefiller-host-port"].split(",")
    # Winner first (least queue), then runners-up by score.
    assert ranked == ["10.0.0.1:8000", "10.0.0.2:8000", "10.0.0.0:8000"]


# ---------- e2e: gateway + 3 simulator replicas ----------

async def _start_app(app, port):
    from aiohttp import web
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", port)
    await site.start()
    return runner


def test_gateway_e2e_prefix_affinity_routing():
    """VERDICT r2 'done' bar: 3 replicas; prefix-affine requests
    demonstrably route to the warm replica via the destination header."""
    from llm_d_tpu.epp.service import build_gateway
    from llm_d_tpu.sim.simulator import SimConfig, build_sim_server

    async def run():
        sim_ports = [free_port() for _ in range(3)]
        runners = []
        for i, port in enumerate(sim_ports):
            srv = build_sim_server(SimConfig(
                model=f"sim-{i}", ttft_ms=1.0, tpot_ms=0.2))
            runners.append(await _start_app(srv.build_app(), port))

        endpoints = [EndpointState(address=f"127.0.0.1:{p}")
                     for p in sim_ports]
        gw = build_gateway(endpoints, scrape_interval_s=0.05)
        gw_port = free_port()
        runners.append(await _start_app(gw.build_app(), gw_port))

        import aiohttp
        async with aiohttp.ClientSession() as sess:
            # Wait for first scrape to mark endpoints ready.
            for _ in range(50):
                if all(e.ready for e in gw.datastore.candidates()):
                    break
                await asyncio.sleep(0.05)
            assert all(e.ready for e in gw.datastore.candidates())

            async def post(prompt):
                async with sess.post(
                        f"http://127.0.0.1:{gw_port}/v1/completions",
                        json={"prompt": prompt, "max_tokens": 4}) as r:
                    assert r.status == 200, await r.text()
                    dest = r.headers[DESTINATION_HEADER]
                    await r.json()
                    return dest

            prompt_a = "alpha " * 200     # long enough for several blocks
            prompt_b = "omega " * 200
            dest_a = await post(prompt_a)
            dest_b = None
            # Route B somewhere; retry until it lands off A's replica (the
            # first B request has no prefix affinity anywhere, so scores tie
            # across replicas and the picker breaks ties randomly).
            for _ in range(20):
                dest_b = await post(prompt_b)
                if dest_b != dest_a:
                    break
            # Warm affinity: repeats must stick to their replica.
            for _ in range(5):
                assert await post(prompt_a) == dest_a
                assert await post(prompt_b) == dest_b

            # Scheduler metrics exposed.
            async with sess.get(
                    f"http://127.0.0.1:{gw_port}/metrics") as r:
                text = await r.text()
            assert "inference_extension_scheduler_e2e_duration_seconds" in text

        for r in runners:
            await r.cleanup()

    asyncio.run(run())


def test_gateway_e2e_sim_metrics_surface():
    """Simulator exposes the vllm:* surface the EPP scrapes."""
    from llm_d_tpu.sim.simulator import SimConfig, build_sim_server

    async def run():
        port = free_port()
        srv = build_sim_server(SimConfig(ttft_ms=1.0, tpot_ms=0.2))
        runner = await _start_app(srv.build_app(), port)
        import aiohttp
        async with aiohttp.ClientSession() as sess:
            async with sess.get(f"http://127.0.0.1:{port}/health") as r:
                assert r.status == 200
            async with sess.get(f"http://127.0.0.1:{port}/v1/models") as r:
                assert r.status == 200
            async with sess.post(
                    f"http://127.0.0.1:{port}/v1/completions",
                    json={"prompt": "hello world", "max_tokens": 3}) as r:
                body = await r.json()
                assert body["usage"]["completion_tokens"] == 3
                assert body["choices"][0]["text"]
            # Streaming chat.
            async with sess.post(
                    f"http://127.0.0.1:{port}/v1/chat/completions",
                    json={"messages": [{"role": "user", "content": "hi"}],
                          "max_tokens": 2, "stream": True}) as r:
                text = await r.text()
                assert "data: [DONE]" in text
            async with sess.get(f"http://127.0.0.1:{port}/metrics") as r:
                m = await r.text()
            for metric in ("vllm:num_requests_running",
                           "vllm:kv_cache_usage_perc",
                           "vllm:generation_tokens_total",
                           "vllm:time_to_first_token_seconds"):
                assert metric in m, metric
        await runner.cleanup()

    asyncio.run(run())


def test_zmq_kv_event_roundtrip():
    """Engine publisher -> ZMQ -> EPP subscriber -> prefix index."""
    import time as _time

    from llm_d_tpu.engine.kv_cache import KVCacheManager
    from llm_d_tpu.events.kv_events import ZmqKvEventPublisher

    port = free_port()
    idx = PrefixIndex()
    from llm_d_tpu.epp.indexer import ZmqEventSubscriber
    sub = ZmqEventSubscriber(idx, bind=f"tcp://127.0.0.1:{port}")
    sub.start()

    pub = ZmqKvEventPublisher(f"tcp://127.0.0.1:{port}",
                              pod_identity="10.9.9.9:8200", model="m",
                              flush_interval_s=0.02)
    kv = KVCacheManager(num_blocks=16, block_size=4)
    pub.attach(kv)
    pub.start()
    _time.sleep(0.3)    # PUB/SUB join

    from llm_d_tpu.engine.request import Request
    from llm_d_tpu.ops.sampling import SamplingParams
    req = Request(request_id="r1", prompt_token_ids=list(range(12)),
                  sampling=SamplingParams())
    kv.allocate(req, 12)
    req.num_computed_tokens = 12
    kv.cache_full_blocks(req)

    deadline = _time.time() + 5
    keys = kv.request_block_hashes(req)
    while _time.time() < deadline:
        if idx.longest_prefix(keys, "10.9.9.9:8200") == 3:
            break
        _time.sleep(0.05)
    assert idx.longest_prefix(keys, "10.9.9.9:8200") == 3
    pub.stop()
    sub.stop()
