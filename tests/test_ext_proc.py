"""ext_proc conformance: a scheduling decision driven end-to-end through the
Envoy gRPC surface (reference: the GAIE EPP behind the FULL_DUPLEX_STREAMED
ext_proc filter, standalone-inference-scheduling/values.yaml:118-181).

The client below speaks the same bidi Process stream Envoy does: request
headers, then buffered body chunks, then reads the header mutation that
carries x-gateway-destination-endpoint.
"""

import json
import queue

import grpc
import pytest

from llm_d_tpu.epp.config import parse_config
from llm_d_tpu.epp.datastore import Datastore, EndpointState
from llm_d_tpu.epp.ext_proc import SERVICE_NAME, METHOD, make_server
from llm_d_tpu.epp.protos import external_processor_pb2 as pb
from llm_d_tpu.epp.scheduler import DESTINATION_HEADER, EppScheduler
from llm_d_tpu.utils.metrics import EppMetrics


def _scheduler(endpoints):
    cfg = parse_config("""
kind: EndpointPickerConfig
plugins:
- type: single-profile-handler
- type: queue-scorer
- type: max-score-picker
schedulingProfiles:
- name: default
  plugins:
  - pluginRef: queue-scorer
  - pluginRef: max-score-picker
""")
    ds = Datastore(endpoints, scrape_interval_s=999)
    return EppScheduler(cfg, ds, metrics=EppMetrics())


@pytest.fixture()
def stack():
    eps = [EndpointState(address="10.0.0.1:8200", ready=True, num_waiting=5),
           EndpointState(address="10.0.0.2:8200", ready=True, num_waiting=0)]
    sched = _scheduler(eps)
    server = make_server(sched, 0, host="127.0.0.1")
    server.start()
    port = server._llmd_port
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    stream = channel.stream_stream(
        f"/{SERVICE_NAME}/{METHOD}",
        request_serializer=pb.ProcessingRequest.SerializeToString,
        response_deserializer=pb.ProcessingResponse.FromString)
    yield sched, stream
    channel.close()
    server.stop(grace=None)


def _headers_msg(path="/v1/completions", extra=(), end_of_stream=False):
    hdrs = [pb.HeaderValue(key=":method", raw_value=b"POST"),
            pb.HeaderValue(key=":path", raw_value=path.encode()),
            pb.HeaderValue(key="x-request-id", value="req-1")]
    hdrs += [pb.HeaderValue(key=k, value=v) for k, v in extra]
    return pb.ProcessingRequest(request_headers=pb.HttpHeaders(
        headers=pb.HeaderMap(headers=hdrs), end_of_stream=end_of_stream))


def _body_msgs(payload: dict, chunks=2):
    raw = json.dumps(payload).encode()
    step = max(1, len(raw) // chunks)
    parts = [raw[i:i + step] for i in range(0, len(raw), step)]
    for i, part in enumerate(parts):
        yield pb.ProcessingRequest(request_body=pb.HttpBody(
            body=part, end_of_stream=i == len(parts) - 1))


def _drive(stream, msgs):
    q = queue.Queue()
    for m in msgs:
        q.put(m)
    q.put(None)

    def gen():
        while True:
            m = q.get()
            if m is None:
                return
            yield m

    return list(stream(gen()))


def test_ext_proc_routes_least_loaded(stack):
    sched, stream = stack
    msgs = [_headers_msg()] + list(_body_msgs(
        {"prompt": "hello world", "max_tokens": 4}))
    responses = _drive(stream, msgs)

    # Headers phase: CONTINUE.  Body phase: mutation with the destination.
    assert responses[0].WhichOneof("response") == "request_headers"
    assert responses[0].request_headers.response.status \
        == pb.CommonResponse.CONTINUE
    final = responses[-1]
    assert final.WhichOneof("response") == "request_body"
    common = final.request_body.response
    assert common.clear_route_cache
    mutated = {o.header.key: (o.header.raw_value.decode() or o.header.value)
               for o in common.header_mutation.set_headers}
    # Least-loaded endpoint (queue 0) wins, exactly like the HTTP plane.
    assert mutated[DESTINATION_HEADER] == "10.0.0.2:8200"
    for o in common.header_mutation.set_headers:
        assert o.append_action \
            == pb.HeaderValueOption.OVERWRITE_IF_EXISTS_OR_ADD


def test_ext_proc_no_endpoints_immediate_503(stack):
    sched, stream = stack
    for e in sched.datastore.candidates():
        e.ready = False
    responses = _drive(stream, [_headers_msg()] + list(_body_msgs(
        {"prompt": "x", "max_tokens": 1})))
    final = responses[-1]
    assert final.WhichOneof("response") == "immediate_response"
    assert final.immediate_response.status.code == 503
    assert "no ready endpoints" in final.immediate_response.body


def test_ext_proc_invalid_json_immediate_400(stack):
    _, stream = stack
    bad = pb.ProcessingRequest(request_body=pb.HttpBody(
        body=b"{not json", end_of_stream=True))
    responses = _drive(stream, [_headers_msg(), bad])
    final = responses[-1]
    assert final.WhichOneof("response") == "immediate_response"
    assert final.immediate_response.status.code == 400


def test_ext_proc_bodyless_get_passthrough(stack):
    _, stream = stack
    responses = _drive(
        stream, [_headers_msg(path="/v1/models", end_of_stream=True)])
    assert len(responses) == 1
    assert responses[0].WhichOneof("response") == "request_headers"


def test_ext_proc_wire_parity_with_http_plane(stack):
    """The same scheduler instance serves both planes: a decision made via
    gRPC is visible in the shared metrics/state exactly like HTTP ones."""
    sched, stream = stack
    before = sched.metrics.render().decode()
    _drive(stream, [_headers_msg()] + list(_body_msgs(
        {"prompt": "hello", "max_tokens": 2})))
    after = sched.metrics.render().decode()
    assert before != after   # scheduler histogram observed the gRPC request


def test_sync_flow_control_gate():
    """Thread-safe admission for the ext_proc plane (advisor r4 medium):
    slots bound concurrency, sheddables never queue, the queue bounds and
    times out, release wakes waiters."""
    import threading
    import time as _time

    from llm_d_tpu.epp.ext_proc import SyncFlowControl

    fc = SyncFlowControl(max_inflight=2, max_queue=1, queue_timeout_s=0.2)
    assert fc.acquire(sheddable=False) == "ok"
    assert fc.acquire(sheddable=False) == "ok"
    # Saturated: sheddable sheds immediately, non-sheddable queues.
    assert fc.acquire(sheddable=True) == "saturated"

    results = []
    t = threading.Thread(
        target=lambda: results.append(fc.acquire(sheddable=False)))
    t.start()
    deadline = _time.monotonic() + 5.0
    while fc._queued != 1 and _time.monotonic() < deadline:
        _time.sleep(0.005)          # wait until the waiter is enqueued
    assert fc._queued == 1
    # Queue now holds one waiter: the next non-sheddable is rejected.
    assert fc.acquire(sheddable=False) == "queue_full"
    fc.release()                      # wakes the queued waiter
    t.join(2)
    assert results == ["ok"]
    # Timeout path: both slots still held (1 original + the waiter's).
    assert fc.acquire(sheddable=False) == "timeout"
    fc.release()
    fc.release()
    assert fc.acquire(sheddable=False) == "ok"


def test_ext_proc_handler_enforces_flow_control():
    """A saturated handler answers 429 before scheduling; release
    restores normal routing."""
    from llm_d_tpu.epp.ext_proc import ExtProcHandler, SyncFlowControl

    sched = _scheduler([EndpointState(address="10.0.0.1:8200", ready=True)])
    fc = SyncFlowControl(max_inflight=1, max_queue=0, queue_timeout_s=0.1)
    handler = ExtProcHandler(sched, flow=fc)
    assert fc.acquire(sheddable=False) == "ok"   # hold the only slot
    resp = handler._schedule({}, b'{"model": "m", "prompt": "x"}')
    assert resp.immediate_response.status.code == 429
    fc.release()
    resp = handler._schedule({}, b'{"model": "m", "prompt": "x"}')
    assert resp.HasField("request_body")
    assert fc._inflight == 0         # schedule released its slot
