"""DP engine group: real data parallelism, not replicated compute.

Parity: a dp=4 x tp=2 group over the 8-device CPU mesh must produce the
same greedy tokens as a single engine.  Proof-of-sharding: each rank's KV
cache and parameters live ONLY on that rank's 2 devices — a request's
attention FLOPs and KV bytes touch 1/4 of the chips (the round-2 engine
device_put everything replicated; reference DP semantics:
decode.yaml:73-93 per-rank engine cores).
"""

import jax
import pytest

from llm_d_tpu.engine.dp_group import DPEngineGroup
from llm_d_tpu.engine.engine import EngineConfig, EngineCore
from llm_d_tpu.engine.request import Request
from llm_d_tpu.ops.sampling import SamplingParams
from llm_d_tpu.parallel.mesh import MeshConfig

ENGINE_KW = dict(model="tiny", block_size=4, num_blocks=64, max_num_seqs=8,
                 max_num_batched_tokens=64, min_token_bucket=16,
                 min_seq_bucket=4)


def greedy_req(rid, prompt, n=6):
    return Request(request_id=rid, prompt_token_ids=list(prompt),
                   sampling=SamplingParams(temperature=0.0, max_tokens=n,
                                           ignore_eos=True))


@pytest.fixture(scope="module")
def baseline():
    return EngineCore(EngineConfig(**ENGINE_KW))


@pytest.fixture(scope="module")
def group(baseline, devices):
    host_params = jax.device_get(baseline.params)
    return DPEngineGroup(
        EngineConfig(**ENGINE_KW, mesh=MeshConfig(tp=2)),
        dp_size=4, params=host_params)


PROMPTS = {
    "r1": [2, 4, 6, 8, 10],
    "r2": [100, 90, 80, 70, 60, 50],
    "r3": [7, 14, 21],
    "r4": [11, 13, 17, 19, 23, 29, 31],
    "r5": [1, 2, 3, 4],
    "r6": [42],
    "r7": [5, 10, 15, 20, 25, 30, 35, 40],
    "r8": [99, 98, 97],
}


@pytest.mark.slow
def test_group_matches_single_engine(baseline, group):
    expected = {}
    for rid, p in PROMPTS.items():
        e = EngineCore(EngineConfig(**ENGINE_KW), params=baseline.params)
        expected[rid] = e.generate([greedy_req(rid, p)])[rid]
    out = group.generate([greedy_req(rid, p) for rid, p in PROMPTS.items()])
    assert out == expected


def test_ranks_own_disjoint_devices(group, devices):
    """The sharding proof: per-rank KV/params touch only that rank's chips."""
    assert len(group.engines) == 4
    device_sets = []
    for e in group.engines:
        kv_devs = e.kv_cache["k"].sharding.device_set
        assert len(kv_devs) == 2, "rank KV must live on its tp=2 submesh only"
        # Params co-located with the KV cache on the same submesh.
        embed_devs = jax.tree.leaves(e.params)[0].sharding.device_set
        assert embed_devs == kv_devs
        device_sets.append(kv_devs)
    # Pairwise disjoint, union covers all 8 chips: no replicated compute.
    union = set()
    for ds in device_sets:
        assert not (union & ds)
        union |= ds
    assert union == set(devices)


def test_rank_kv_shard_shape(group):
    """Per-device KV bytes: full slots per rank (its own pool), folded head
    dim split over tp=2 — versus round 2 where every device held every
    rank's cache."""
    e = group.engines[0]
    k = e.kv_cache["k"]
    L, slots, F = k.shape
    for shard in k.addressable_shards:
        assert shard.data.shape == (L, slots, F // 2)


def test_dispatch_balances_load(group):
    reqs = [greedy_req(f"lb-{i}", [i + 1, i + 2, i + 3], 3) for i in range(8)]
    for r in reqs:
        group.add_request(r)
    per_rank = [e.scheduler.num_waiting + e.scheduler.num_running
                for e in group.engines]
    assert per_rank == [2, 2, 2, 2]
    while group.has_work():
        group.step()
    assert all(len(r.output_token_ids) == 3 for r in reqs)


def test_abort_routes_to_owning_rank(group):
    r = greedy_req("kill-me", [1, 2, 3], 50)
    group.add_request(r)
    group.step()
    group.abort_request("kill-me")
    assert all(rr.request_id != "kill-me"
               for e in group.engines for rr in e.scheduler.running)


def test_aggregated_gauges(group):
    reqs = [greedy_req(f"g-{i}", [i + 1] * 3, 2) for i in range(4)]
    for r in reqs:
        group.add_request(r)
    group.step()
    text = group.metrics.render().decode()
    assert "vllm:num_requests_running" in text
    while group.has_work():
        group.step()
