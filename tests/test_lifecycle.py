"""Request lifecycle: deadline propagation, SLO classes, graceful drain.

Covers the wire contract (`llm_d_tpu.utils.lifecycle`), the model server's
deadline 504 / drain protocol, the engine's deadline metrics + block
accounting, the P->D cancellation release, and the sim mirror the chaos
suite drives.  All CPU, tier-1 safe.
"""

import asyncio
import socket
import threading
import time

import pytest
import requests

from llm_d_tpu.engine.engine import EngineConfig, EngineCore
from llm_d_tpu.engine.request import Request, RequestState
from llm_d_tpu.ops.sampling import SamplingParams
from llm_d_tpu.transfer import KVConnectorConfig, TpuConnector
from llm_d_tpu.utils.faultinject import FaultInjector, install, reset
from llm_d_tpu.utils.lifecycle import (
    CRITICALITY_HEADER,
    DEADLINE_ABS_HEADER,
    DEADLINE_EXCEEDED_HEADER,
    DEADLINE_MS_HEADER,
    DRAINING_HEADER,
    parse_criticality,
    parse_deadline,
)

ENGINE_KW = dict(model="tiny", block_size=4, num_blocks=64, max_num_seqs=8,
                 max_num_batched_tokens=64, min_token_bucket=16,
                 min_seq_bucket=4)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def greedy_req(rid, prompt, n=4, **kw):
    return Request(request_id=rid, prompt_token_ids=list(prompt),
                   sampling=SamplingParams(temperature=0.0, max_tokens=n,
                                           ignore_eos=True), **kw)


@pytest.fixture()
def inject():
    def make(spec: str = "", seed: int = 0) -> FaultInjector:
        return install(FaultInjector.from_spec(spec, seed=seed))
    yield make
    reset()


async def _start_app(app, port):
    from aiohttp import web
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", port)
    await site.start()
    return runner


# ---------------------------------------------------------------------------
# wire contract
# ---------------------------------------------------------------------------

def test_parse_criticality_classes_and_errors():
    assert parse_criticality({}, {}) == "standard"
    assert parse_criticality({CRITICALITY_HEADER: "Critical"}, {}) \
        == "critical"
    assert parse_criticality({}, {"criticality": "sheddable"}) \
        == "sheddable"
    # Header wins over body; unknown class is a client error.
    assert parse_criticality({CRITICALITY_HEADER: "critical"},
                             {"criticality": "sheddable"}) == "critical"
    with pytest.raises(ValueError):
        parse_criticality({CRITICALITY_HEADER: "urgentest"}, {})


def test_parse_deadline_precedence_and_errors():
    now = 1000.0
    # Absolute header wins (already stamped by an earlier hop).
    assert parse_deadline({DEADLINE_ABS_HEADER: "1234.5",
                           DEADLINE_MS_HEADER: "50"}, {}, now=now) == 1234.5
    assert parse_deadline({DEADLINE_MS_HEADER: "500"}, {}, now=now) \
        == pytest.approx(1000.5)
    # OpenAI-body timeout alias is SECONDS.
    assert parse_deadline({}, {"timeout": 2}, now=now) \
        == pytest.approx(1002.0)
    assert parse_deadline({}, {}) is None
    for headers, body in (
            ({DEADLINE_MS_HEADER: "banana"}, {}),
            ({DEADLINE_ABS_HEADER: "soon"}, {}),
            ({DEADLINE_MS_HEADER: "-5"}, {}),
            ({}, {"timeout": "never"})):
        with pytest.raises(ValueError):
            parse_deadline(headers, body)


# ---------------------------------------------------------------------------
# engine: deadline metrics + block accounting
# ---------------------------------------------------------------------------

def test_engine_deadline_rejection_metrics_and_blocks():
    engine = EngineCore(EngineConfig(**ENGINE_KW))
    late = greedy_req("late", [1, 2, 3, 4], 8)
    late.deadline = time.monotonic() - 0.01
    late.criticality = "sheddable"
    engine.add_request(late)
    outs = engine.step()
    assert [o.finish_reason for o in outs
            if o.request_id == "late"] == ["deadline"]
    assert not late.block_ids and not engine.scheduler.has_work()
    text = engine.metrics.render().decode()
    assert "llmd_tpu:deadline_exceeded_total" in text
    assert 'criticality="sheddable"' in text
    # Queue-wait histogram appears once something real is scheduled.
    ok = greedy_req("ok", [1, 2, 3, 4], 2)
    engine.generate([ok])
    text = engine.metrics.render().decode()
    assert "llmd_tpu:request_queue_wait_seconds" in text
    assert 'criticality="standard"' in text


# ---------------------------------------------------------------------------
# P->D: cancellation propagates to the producer's pinned blocks
# ---------------------------------------------------------------------------

def _drive(engine, until, max_steps=2000):
    outs = []
    for _ in range(max_steps):
        outs.extend(engine.step())
        if until():
            return outs
        if not engine.scheduler.has_work():
            time.sleep(0.002)
    raise AssertionError("condition not reached (hung request?)")


def _remote_prefill(producer, rid, prompt):
    preq = greedy_req(rid, prompt, 1, do_remote_decode=True)
    producer.add_request(preq)
    _drive(producer,
           lambda: preq.state == RequestState.FINISHED_REMOTE_PREFILL)
    return preq.kv_transfer_params


@pytest.fixture(scope="module")
def pd_engines():
    baseline = EngineCore(EngineConfig(**ENGINE_KW))
    producer = EngineCore(EngineConfig(**ENGINE_KW), params=baseline.params)
    producer.kv_connector = TpuConnector(
        KVConnectorConfig(kv_role="kv_producer", host="127.0.0.1"))
    yield baseline, producer
    producer.kv_connector.close()


def test_consumer_abort_releases_producer_pins(pd_engines, inject):
    """Cancel while the KV pull is in flight: the consumer's abort sends
    an eager release so the producer's pinned prefill blocks free NOW,
    not at the 120s pin timeout."""
    baseline, producer = pd_engines
    inj = inject()
    inj.add_rule("kv.pull", latency_s=0.3, label="none")   # stall, no fail
    consumer = EngineCore(EngineConfig(**ENGINE_KW), params=baseline.params)
    consumer.kv_connector = TpuConnector(KVConnectorConfig(
        kv_role="kv_consumer", timeout_ms=2000))
    try:
        params = _remote_prefill(producer, "cancelme", [5, 4, 3, 2, 1])
        assert "cancelme" in producer.pinned_transfers
        dreq = greedy_req("cancelme", [5, 4, 3, 2, 1], 4,
                          do_remote_prefill=True, kv_transfer_params=params)
        consumer.add_request(dreq)        # pull stalled at the fault point
        consumer.abort_request("cancelme")
        # Producer pins release via the cancel-release, well inside the
        # pin timeout (drive pumps drain_released).
        _drive(producer, lambda: not producer.pinned_transfers)
        _drive(consumer, lambda: dreq.state.finished)
        assert dreq.state == RequestState.FINISHED_ABORTED
    finally:
        consumer.kv_connector.close()


def test_consumer_deadline_expiry_drops_pull_before_decode(pd_engines):
    """A pull that lands after the deadline is dropped at poll() — no
    local blocks are allocated for a request the client wrote off — and
    the producer's pins still free."""
    baseline, producer = pd_engines
    consumer = EngineCore(EngineConfig(**ENGINE_KW), params=baseline.params)
    consumer.kv_connector = TpuConnector(KVConnectorConfig(
        kv_role="kv_consumer", timeout_ms=2000))
    try:
        params = _remote_prefill(producer, "tooslow", [9, 9, 8, 8])
        dreq = greedy_req("tooslow", [9, 9, 8, 8], 4,
                          do_remote_prefill=True, kv_transfer_params=params)
        dreq.deadline = time.monotonic() - 0.01
        consumer.add_request(dreq)
        outs = _drive(consumer, lambda: dreq.state.finished)
        assert [o.finish_reason for o in outs
                if o.request_id == "tooslow"] == ["deadline"]
        assert not dreq.block_ids
        _drive(producer, lambda: not producer.pinned_transfers)
    finally:
        consumer.kv_connector.close()


# ---------------------------------------------------------------------------
# model server: 504 contract + drain protocol over real HTTP
# ---------------------------------------------------------------------------

def _start_server_thread(server, port):
    from aiohttp import web
    started = threading.Event()

    def run():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(server.build_app())
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", port)
        loop.run_until_complete(site.start())
        started.set()
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(timeout=30)
    url = f"http://127.0.0.1:{port}"
    for _ in range(100):
        try:
            if requests.get(url + "/v1/models", timeout=5).status_code == 200:
                break
        except requests.ConnectionError:
            pass
        time.sleep(0.1)
    return url


@pytest.fixture(scope="module")
def lifecycle_server():
    from llm_d_tpu.server.openai import build_server
    cfg = EngineConfig(**ENGINE_KW)
    server = build_server(cfg)
    url = _start_server_thread(server, free_port())
    return server, url


def test_server_expired_deadline_is_504(lifecycle_server):
    _server, url = lifecycle_server
    r = requests.post(url + "/v1/completions",
                      json={"prompt": "hello", "max_tokens": 2},
                      headers={DEADLINE_ABS_HEADER: str(time.time() - 5)})
    assert r.status_code == 504
    assert r.headers.get(DEADLINE_EXCEEDED_HEADER) == "1"
    assert "deadline" in r.json()["error"]


def test_server_generous_deadline_succeeds(lifecycle_server):
    _server, url = lifecycle_server
    r = requests.post(url + "/v1/completions",
                      json={"prompt": "hello", "max_tokens": 2,
                            "timeout": 120},
                      headers={CRITICALITY_HEADER: "critical"})
    assert r.status_code == 200
    assert r.json()["choices"][0]["finish_reason"] in ("length", "stop")


def test_server_invalid_lifecycle_inputs_400(lifecycle_server):
    _server, url = lifecycle_server
    r = requests.post(url + "/v1/completions",
                      json={"prompt": "x", "max_tokens": 1},
                      headers={CRITICALITY_HEADER: "mega"})
    assert r.status_code == 400
    r = requests.post(url + "/v1/completions",
                      json={"prompt": "x", "max_tokens": 1},
                      headers={DEADLINE_MS_HEADER: "banana"})
    assert r.status_code == 400


def test_server_drain_protocol(lifecycle_server):
    """Runs LAST against this fixture server (drain is one-way): the
    drain endpoint flips readiness, refuses new inference with 503 +
    x-llmd-draining, exports drain_state, and liveness stays up."""
    _server, url = lifecycle_server
    r = requests.post(url + "/admin/drain")
    assert r.status_code == 200
    assert r.json()["status"] == "draining"
    assert requests.get(url + "/v1/models").status_code == 503
    assert requests.get(url + "/health").status_code == 200   # liveness
    r = requests.post(url + "/v1/completions",
                      json={"prompt": "nope", "max_tokens": 1})
    assert r.status_code == 503
    assert r.headers.get(DRAINING_HEADER) == "1"
    from llm_d_tpu.utils.metrics import parse_prometheus_text
    m = parse_prometheus_text(requests.get(url + "/metrics").text)
    assert m.get("llmd_tpu:drain_state") == 1.0
    # Idempotent.
    assert requests.post(url + "/admin/drain").status_code == 200


# ---------------------------------------------------------------------------
# sim mirror: the same contract, no accelerator
# ---------------------------------------------------------------------------

def test_sim_deadline_and_drain_mirror():
    from llm_d_tpu.sim.simulator import SimConfig, build_sim_server

    async def run():
        port = free_port()
        srv = build_sim_server(SimConfig(model="sim", ttft_ms=1.0,
                                         tpot_ms=0.2))
        runner = await _start_app(srv.build_app(), port)
        url = f"http://127.0.0.1:{port}"
        import aiohttp
        try:
            async with aiohttp.ClientSession() as sess:
                # Expired deadline -> 504 + marker, mirroring the server.
                async with sess.post(f"{url}/v1/completions", json={
                        "prompt": "late", "max_tokens": 2},
                        headers={DEADLINE_ABS_HEADER:
                                 str(time.time() - 5)}) as r:
                    assert r.status == 504
                    assert r.headers.get(DEADLINE_EXCEEDED_HEADER) == "1"
                # Healthy request with budget -> 200.
                async with sess.post(f"{url}/v1/completions", json={
                        "prompt": "ok", "max_tokens": 2},
                        headers={DEADLINE_MS_HEADER: "30000",
                                 CRITICALITY_HEADER: "critical"}) as r:
                    assert r.status == 200
                async with sess.get(f"{url}/metrics") as r:
                    text = await r.text()
                assert "llmd_tpu:deadline_exceeded_total" in text
                assert "llmd_tpu:request_queue_wait_seconds" in text
                # Drain: readiness flips, new work 503s, metric exports.
                async with sess.post(f"{url}/admin/drain") as r:
                    assert r.status == 200
                async with sess.get(f"{url}/v1/models") as r:
                    assert r.status == 503
                async with sess.post(f"{url}/v1/completions", json={
                        "prompt": "x", "max_tokens": 1}) as r:
                    assert r.status == 503
                    assert r.headers.get(DRAINING_HEADER) == "1"
                async with sess.get(f"{url}/metrics") as r:
                    from llm_d_tpu.utils.metrics import (
                        parse_prometheus_text)
                    m = parse_prometheus_text(await r.text())
                    assert m.get("llmd_tpu:drain_state") == 1.0
        finally:
            await runner.cleanup()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# gateway: deadline 504 + lifecycle header propagation
# ---------------------------------------------------------------------------

def test_gateway_expired_deadline_504_and_header_propagation():
    """The gateway stamps the ABSOLUTE deadline and forwards it with the
    criticality class; an expired budget 504s at the gateway without
    burning an upstream forward."""
    import aiohttp

    from llm_d_tpu.epp.datastore import EndpointState
    from llm_d_tpu.epp.service import build_gateway
    from llm_d_tpu.sim.simulator import SimConfig, build_sim_server

    async def run():
        sim_port = free_port()
        srv = build_sim_server(SimConfig(model="sim", ttft_ms=1.0,
                                         tpot_ms=0.2))
        runners = [await _start_app(srv.build_app(), sim_port)]
        gw = build_gateway(
            [EndpointState(address=f"127.0.0.1:{sim_port}")],
            scrape_interval_s=0.05)
        gw_port = free_port()
        runners.append(await _start_app(gw.build_app(), gw_port))
        url = f"http://127.0.0.1:{gw_port}/v1/completions"
        try:
            async with aiohttp.ClientSession() as sess:
                for _ in range(100):
                    if all(e.ready for e in gw.datastore.candidates()):
                        break
                    await asyncio.sleep(0.05)
                async with sess.post(url, json={
                        "prompt": "late", "max_tokens": 2},
                        headers={DEADLINE_MS_HEADER: "0.5"}) as r:
                    # 0.5ms budget: expired by the time scheduling runs
                    # (scrape wait above burned it) — or in a freakishly
                    # fast world the sim honors it; both carry the marker
                    # path.  Retry once with an already-expired absolute
                    # header for determinism.
                    pass
                async with sess.post(url, json={
                        "prompt": "late", "max_tokens": 2},
                        headers={DEADLINE_ABS_HEADER:
                                 str(time.time() - 1)}) as r:
                    assert r.status == 504
                    assert r.headers.get(DEADLINE_EXCEEDED_HEADER) == "1"
                # A live request rides the absolute deadline + class to
                # the replica (sim parses both without error) and wins.
                async with sess.post(url, json={
                        "prompt": "ok", "max_tokens": 2,
                        "criticality": "critical"},
                        headers={DEADLINE_MS_HEADER: "30000"}) as r:
                    assert r.status == 200
                async with sess.get(
                        f"http://127.0.0.1:{gw_port}/metrics") as r:
                    text = await r.text()
                assert "llmd_tpu:gateway_deadline_exceeded_total" in text
        finally:
            for r in runners:
                await r.cleanup()

    asyncio.run(run())
