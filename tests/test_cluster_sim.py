"""Cluster-scale chaos testbed (sim/cluster.py).

Fast tier: ≤12-replica scenarios proving each mechanism — virtual-clock
speed, zone-kill mid-stream failover with zero client-visible breaks,
P↔D partition → prefill local-fallback, stragglers in the tail,
flow-control shedding, deadline misses, seeded `LLMD_FAULTS` cluster
points (`cluster.partition`, `cluster.zone_kill`, `cluster.straggler`),
the closed-loop WVA autoscaler, and the byte-identical-scoreboard
contract.

Slow tier: the ≥100-replica acceptance scenario — zone kill + P↔D
partition + stragglers under multi-tenant diurnal load, judged entirely
by the scoreboard.
"""

import json
import time

import pytest

from llm_d_tpu.sim.cluster import (
    ClusterSim,
    FaultEvent,
    Scenario,
    tenant_bucket,
)
from llm_d_tpu.utils.faultinject import FAULT_POINTS
from llm_d_tpu.utils.lifecycle import DEFAULT_TENANT, parse_tenant


def _run(d):
    sim = ClusterSim(Scenario.from_dict(d))
    return sim, sim.run()


# ---------------------------------------------------------------------------
# Registration / helpers
# ---------------------------------------------------------------------------


def test_cluster_fault_points_registered():
    for point in ("cluster.partition", "cluster.zone_kill",
                  "cluster.straggler"):
        assert point in FAULT_POINTS


def test_tenant_header_parsing():
    assert parse_tenant({"x-llmd-tenant": "acme"}) == "acme"
    assert parse_tenant({}, {"tenant": "bulk"}) == "bulk"
    assert parse_tenant({"x-llmd-tenant": "  "}) == DEFAULT_TENANT
    assert parse_tenant({}) == DEFAULT_TENANT


def test_tenant_bucket_is_stable_and_bounded():
    # sha256-based: stable across processes (unlike hash()), bounded by
    # the bucket count.
    assert tenant_bucket("acme", 8) == tenant_bucket("acme", 8)
    assert 0 <= int(tenant_bucket("acme", 8)) < 8
    buckets = {tenant_bucket(f"t{i}", 4) for i in range(64)}
    assert buckets == {"0", "1", "2", "3"}


# ---------------------------------------------------------------------------
# Core mechanisms (fast tier)
# ---------------------------------------------------------------------------


def test_virtual_clock_outruns_wall_clock():
    t0 = time.perf_counter()
    _sim, rep = _run({
        "name": "clock", "seed": 1, "duration_s": 300.0,
        "replicas": [{"zone": "zone-a", "count": 2}],
        "tenants": [{"name": "t", "qps": 0.2, "max_tokens": 4}],
    })
    wall = time.perf_counter() - t0
    assert wall < 300.0 / 10          # 300 virtual seconds, CPU seconds
    assert rep["classes"]["standard"]["requests"] > 20
    # The time patch must be fully unwound after run().
    assert abs(time.time() - time.monotonic()) > 1e6


def test_zone_kill_streams_resume_with_zero_client_breaks():
    sim, rep = _run({
        "name": "zone-kill", "seed": 3, "duration_s": 12.0,
        "replicas": [{"zone": "zone-a", "count": 2},
                     {"zone": "zone-b", "count": 2}],
        "tenants": [{"name": "acme", "qps": 10.0,
                     "criticality": "critical", "max_tokens": 300}],
        "faults": [{"at_s": 4.0, "kind": "zone_kill", "target": "zone-b"}],
        "breaker_failures": 1,
    })
    cell = rep["tenants"]["acme"]["critical"]
    assert cell["stream_breaks"] == 0
    assert cell["ok"] == cell["requests"] > 50
    assert sum(cell["resumes"].values()) > 0    # kills landed mid-stream
    # Breaker convergence: every dead endpoint is tripped (non-closed)
    # or scrape-excluded from routing.
    assert set(rep["fleet"]["dead_ever"]) == {"zone-b-0:8200",
                                             "zone-b-1:8200"}
    for addr in rep["fleet"]["dead_ever"]:
        converged = (rep["fleet"]["breakers"][addr] != "closed"
                     or not sim.datastore.endpoints[addr].ready)
        assert converged, addr


def test_pd_partition_falls_back_to_local_prefill():
    _sim, rep = _run({
        "name": "pd-cut", "seed": 11, "duration_s": 20.0,
        "pd_threshold": 64,
        "replicas": [{"zone": "zone-a", "count": 3, "role": "decode"},
                     {"zone": "zone-p", "count": 2, "role": "prefill"}],
        "tenants": [{"name": "ragco", "qps": 2.0, "kind": "rag",
                     "criticality": "standard", "max_tokens": 24}],
        "faults": [
            {"at_s": 5.0, "kind": "partition",
             "target": "role:decode|role:prefill"},
            {"at_s": 14.0, "kind": "partition_heal",
             "target": "role:decode|role:prefill"},
        ],
        "breaker_failures": 1,
    })
    cell = rep["tenants"]["ragco"]["standard"]
    assert cell["prefill_fallback"] > 0     # cut window recomputed locally
    assert cell["stream_breaks"] == 0       # fallback is never a break
    assert cell["ok"] == cell["requests"]


def test_straggler_stretches_the_tail_not_the_median():
    d = {
        "name": "straggle", "seed": 5, "duration_s": 20.0,
        "replicas": [{"zone": "zone-a", "count": 4}],
        "tenants": [{"name": "t", "qps": 4.0, "max_tokens": 16}],
        "faults": [{"at_s": 2.0, "kind": "straggler",
                    "target": "zone-a-0:8200", "factor": 6.0}],
    }
    _sim, rep = _run(d)
    cell = rep["classes"]["standard"]
    assert cell["tpot_p50_ms"] == pytest.approx(10.0, abs=2.0)
    assert cell["tpot_p99_ms"] >= 4 * cell["tpot_p50_ms"]


def test_seeded_llmd_faults_drive_cluster_points():
    # The LLMD_FAULTS grammar reaches the cluster points: a seeded
    # one-shot cluster.zone_kill rule gang-kills the matched zone.
    _sim, rep = _run({
        "name": "grammar", "seed": 9, "duration_s": 15.0,
        "replicas": [{"zone": "zone-a", "count": 2},
                     {"zone": "zone-b", "count": 2}],
        "tenants": [{"name": "t", "qps": 3.0, "max_tokens": 8}],
        "llmd_faults": "cluster.zone_kill:count=1,after=4,match=zone-b",
        "breaker_failures": 1,
    })
    assert set(rep["fleet"]["dead_ever"]) == {"zone-b-0:8200",
                                             "zone-b-1:8200"}
    kinds = [k for _, k, tgt in rep["fleet"]["faults_applied"]
             if tgt == "zone-b"]
    assert "zone_kill" in kinds


def test_injected_partition_point_breaks_links():
    # cluster.partition keyed "src->dst": a probabilistic link fault on
    # every hop still ends with every request served (retry/resume).
    _sim, rep = _run({
        "name": "flaky-links", "seed": 13, "duration_s": 15.0,
        "replicas": [{"zone": "zone-a", "count": 3}],
        "tenants": [{"name": "t", "qps": 3.0,
                     "criticality": "critical", "max_tokens": 12}],
        "llmd_faults": "cluster.partition:p=0.05",
        "breaker_failures": 3,
    })
    cell = rep["tenants"]["t"]["critical"]
    assert cell["requests"] > 20
    assert cell["stream_breaks"] == 0
    assert cell["ok"] == cell["requests"]


def test_flow_control_sheds_sheddable_keeps_critical():
    _sim, rep = _run({
        "name": "overload", "seed": 21, "duration_s": 10.0,
        "replicas": [{"zone": "zone-a", "count": 1, "max_num_seqs": 2}],
        "tenants": [
            {"name": "vip", "qps": 2.0, "criticality": "critical",
             "max_tokens": 40},
            {"name": "bulk", "qps": 30.0, "criticality": "sheddable",
             "max_tokens": 40},
        ],
        "max_inflight": 4, "max_queue": 4,
    })
    assert rep["tenants"]["bulk"]["sheddable"]["shed"] > 0
    vip = rep["tenants"]["vip"]["critical"]
    assert vip["shed"] == 0
    assert vip["ok"] == vip["requests"]


def test_deadlines_expire_and_are_counted():
    _sim, rep = _run({
        "name": "deadlines", "seed": 17, "duration_s": 10.0,
        "replicas": [{"zone": "zone-a", "count": 1, "max_num_seqs": 2,
                      "tpot_ms": 20.0}],
        "tenants": [{"name": "t", "qps": 8.0, "max_tokens": 50,
                     "deadline_ms": 300}],
    })
    cell = rep["tenants"]["t"]["standard"]
    assert cell["deadline_miss"] > 0
    assert cell["deadline_miss"] + cell["ok"] + cell["rejected"] \
        == cell["requests"]
    assert cell["attainment"] < 1.0


def test_drain_event_routes_away_without_breaks():
    _sim, rep = _run({
        "name": "drain", "seed": 23, "duration_s": 15.0,
        "replicas": [{"zone": "zone-a", "count": 3}],
        "tenants": [{"name": "t", "qps": 5.0, "max_tokens": 30}],
        "faults": [{"at_s": 5.0, "kind": "drain",
                    "target": "zone-a-1:8200"}],
    })
    cell = rep["classes"]["standard"]
    assert cell["stream_breaks"] == 0
    assert cell["ok"] == cell["requests"]


def test_multi_tenant_prefix_pools_and_agent_sessions():
    _sim, rep = _run({
        "name": "tenants", "seed": 29, "duration_s": 20.0,
        "replicas": [{"zone": "zone-a", "count": 2}],
        "tenants": [
            {"name": "acme", "qps": 2.0, "kind": "chat",
             "prefix_groups": 2, "max_tokens": 8},
            {"name": "agents", "qps": 0.5, "kind": "agent", "turns": 3,
             "criticality": {"standard": 0.5, "sheddable": 0.5},
             "max_tokens": 8},
        ],
    })
    assert "acme" in rep["tenants"] and "agents" in rep["tenants"]
    agent_reqs = sum(c["requests"] for c in rep["tenants"]["agents"]
                     .values())
    assert agent_reqs >= 3              # at least one full session
    # Per-class attainment buckets exist for every class seen.
    for crit in rep["classes"]:
        assert crit in rep["attainment"]


def test_trace_replay_issues_records_verbatim():
    trace = [{"at_s": 1.0 + 0.25 * i, "tenant": "replayed",
              "prompt": f"trace prompt {i}", "max_tokens": 6,
              "criticality": "critical"} for i in range(12)]
    _sim, rep = _run({
        "name": "replay", "seed": 31, "duration_s": 8.0,
        "replicas": [{"zone": "zone-a", "count": 2}],
        "tenants": [], "trace": trace,
    })
    cell = rep["tenants"]["replayed"]["critical"]
    assert cell["requests"] == 12
    assert cell["ok"] == 12


def test_scoreboard_is_byte_identical_across_runs():
    d = {
        "name": "determinism", "seed": 37, "duration_s": 15.0,
        "pd_threshold": 64,
        "replicas": [{"zone": "zone-a", "count": 3, "role": "decode"},
                     {"zone": "zone-b", "count": 3, "role": "decode"},
                     {"zone": "zone-p", "count": 2, "role": "prefill"}],
        "tenants": [
            {"name": "acme", "qps": 4.0, "criticality": "critical",
             "max_tokens": 40},
            {"name": "ragco", "qps": 1.0, "kind": "rag",
             "max_tokens": 16},
        ],
        "diurnal": {"period_s": 15.0, "low": 0.3, "high": 1.0},
        "faults": [{"at_s": 5.0, "kind": "zone_kill", "target": "zone-b"},
                   {"at_s": 10.0, "kind": "zone_restore",
                    "target": "zone-b", "restart_delay_s": 2.0}],
        "llmd_faults": "cluster.straggler:p=0.02",
        "breaker_failures": 1,
    }
    j1 = ClusterSim(Scenario.from_dict(d)).run_json()
    j2 = ClusterSim(Scenario.from_dict(d)).run_json()
    assert j1 == j2
    other = ClusterSim(Scenario.from_dict(dict(d, seed=38))).run_json()
    assert other != j1                  # the seed actually matters


def test_fault_event_from_dict_keeps_params():
    ev = FaultEvent.from_dict({"at_s": 3, "kind": "straggler",
                               "target": "a:1", "factor": 5.0})
    assert ev.at_s == 3.0 and ev.params == {"factor": 5.0}


# ---------------------------------------------------------------------------
# Closed-loop autoscaling (fast tier)
# ---------------------------------------------------------------------------


def test_wva_closed_loop_scales_up_on_burst_and_down_at_trough():
    # prefix_groups is high on purpose: with the default 4 pools every
    # prompt is a full prefix-cache hit on a pinned replica, and the
    # weight-3 prefix scorer beats the weight-2 queue scorer by exactly
    # the margin of a full match — fresh replicas then never win a pick
    # and autoscaling is useless.  Diverse traffic is what autoscaling
    # can actually absorb; the pinning arithmetic itself is documented
    # in docs/cluster-sim.md.
    def scenario(auto):
        return {
            "name": "wva-loop", "seed": 41, "duration_s": 60.0,
            "replicas": [{"zone": "zone-a", "count": 2,
                          "max_num_seqs": 4}],
            "tenants": [{"name": "acme", "qps": 40.0,
                         "prefix_groups": 500,
                         "criticality": "critical", "max_tokens": 24}],
            "diurnal": {"period_s": 60.0, "low": 0.05, "high": 1.0},
            "autoscale": {"enabled": auto, "min_replicas": 2,
                          "max_replicas": 12, "target_saturation": 0.6,
                          "interval_s": 5.0, "zone": "zone-a",
                          "startup_delay_s": 2.0},
            "scrape_interval_s": 1.0,
        }

    _, base = _run(scenario(False))
    sim, rep = _run(scenario(True))
    # Scale-up happened mid-burst and receded by the trough.
    assert rep["fleet"]["replicas_peak"] > 2
    assert rep["fleet"]["replicas_final"] < rep["fleet"]["replicas_peak"]
    # The whole cycle — including every drain-based scale-down — broke
    # zero streams and shed nothing critical.
    cell = rep["tenants"]["acme"]["critical"]
    base_cell = base["tenants"]["acme"]["critical"]
    assert cell["stream_breaks"] == 0
    assert cell["shed"] == 0
    assert cell["ok"] == cell["requests"]
    # Scale-up beat the queue: against the identical seed with the
    # autoscaler off, capacity arriving mid-burst collapses the tail and
    # lifts attainment from a failing grade to near-perfect.
    assert cell["ttft_p99_ms"] < base_cell["ttft_p99_ms"] / 2
    assert cell["attainment"] > base_cell["attainment"] + 0.3
    assert cell["attainment"] > 0.9
    assert sim.wva is not None and sim.wva.desired_replicas >= 2


# ---------------------------------------------------------------------------
# Transfer-cost-aware KV placement (fast tier)
# ---------------------------------------------------------------------------


def test_kv_placement_unpins_fully_cached_traffic():
    # Re-seeds the docs/cluster-sim.md pinning case study: with 2 prefix
    # pools every prompt is a full cache hit on a pinned replica, and
    # the weight-3 prefix scorer outbids the weight-2 queue scorer by
    # the margin of a full match — fresh autoscaled replicas never win a
    # pick, so scale-up barely moves the needle.  The kv-placement cost
    # scorer prices the SAME cache hit as avoided-prefill milliseconds,
    # which saturates against unbounded queue cost: identical seed,
    # identical autoscaling, and the tail collapses.
    def scenario(kv):
        return {
            "name": "unpin", "seed": 43, "duration_s": 60.0,
            "replicas": [{"zone": "zone-a", "count": 2,
                          "max_num_seqs": 4}],
            "tenants": [{"name": "acme", "qps": 40.0,
                         "prefix_groups": 2, "prefix_len": 100,
                         "criticality": "critical", "max_tokens": 24}],
            "diurnal": {"period_s": 60.0, "low": 0.05, "high": 1.0},
            "autoscale": {"enabled": True, "min_replicas": 2,
                          "max_replicas": 12, "target_saturation": 0.6,
                          "interval_s": 5.0, "zone": "zone-a",
                          "startup_delay_s": 2.0},
            "scrape_interval_s": 1.0,
            "kv_placement": kv,
        }

    _, base = _run(scenario(False))
    _, rep = _run(scenario(True))
    cell = rep["tenants"]["acme"]["critical"]
    base_cell = base["tenants"]["acme"]["critical"]
    # Both arms: fully-cached traffic, zero breaks, nothing dropped.
    for c in (cell, base_cell):
        assert c["stream_breaks"] == 0
        assert c["ok"] == c["requests"]
        assert c["prefix_hit_rate"] > 0.8
    # Weight-3 stays pinned (failing attainment despite the autoscaler);
    # the cost scorer un-pins: tail collapses, attainment recovers, and
    # the prefix-hit rate does NOT pay for it — missing blocks are
    # restored from peers instead of recomputed cold.
    assert cell["ttft_p99_ms"] < base_cell["ttft_p99_ms"] * 0.8
    assert cell["attainment"] > base_cell["attainment"] + 0.1
    assert cell["attainment"] > 0.95
    assert cell["prefix_hit_rate"] >= base_cell["prefix_hit_rate"] - 0.01
    verdicts = cell["kv_verdicts"]
    assert verdicts.get("local_hit", 0) > 0.9 * cell["requests"]
    assert base_cell["kv_verdicts"] == {}      # control arm has no scorer


def test_kv_placement_report_is_byte_identical():
    d = {
        "name": "kv-det", "seed": 47, "duration_s": 20.0,
        "replicas": [{"zone": "zone-a", "count": 4, "max_num_seqs": 2}],
        "tenants": [{"name": "acme", "qps": 8.0, "prefix_groups": 3,
                     "prefix_len": 60, "max_tokens": 12}],
        "faults": [{"at_s": 8.0, "kind": "replica_kill",
                    "target": "zone-a-0:8200"},
                   {"at_s": 14.0, "kind": "replica_restore",
                    "target": "zone-a-0:8200"}],
        "kv_placement": True,
    }
    j1 = ClusterSim(Scenario.from_dict(d)).run_json()
    j2 = ClusterSim(Scenario.from_dict(d)).run_json()
    assert j1 == j2
    cls = json.loads(j1)["classes"]["standard"]
    # The fabric actually moved bytes: kill/restore forces peer restores.
    assert cls["kv_verdicts"].get("peer_restore", 0) > 0
    assert cls["restore_bytes"] > 0


# ---------------------------------------------------------------------------
# Acceptance scenario (slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_acceptance_100_replica_incident_scoreboard():
    """The issue's acceptance gate: a seeded ≥100-replica fleet through
    zone kill + P↔D partition + stragglers under diurnal multi-tenant
    load — zero client-visible breaks for the critical class, breaker
    convergence on every dead endpoint, per-tenant scoreboards, and a
    byte-identical report across two runs of the same seed."""
    d = {
        "name": "acceptance", "seed": 1009, "duration_s": 120.0,
        "pd_threshold": 64,
        "replicas": [
            {"zone": "zone-a", "count": 48, "role": "decode"},
            {"zone": "zone-b", "count": 48, "role": "decode"},
            {"zone": "zone-p", "count": 8, "role": "prefill"},
        ],
        "tenants": [
            # Streams long enough (~1 s) that several are always
            # mid-flight on zone-b when the kill lands — the resume
            # path must fire, not dodge the incident.  prefix_groups
            # must span the fleet: with the default 4 pools the
            # prefix scorer pins ALL of acme to ≤4 replicas, which at
            # this seed all sit in zone-a and the kill hits nothing
            # (the docs/cluster-sim.md pinning case study, observed
            # live).
            {"name": "acme", "qps": 12.0, "criticality": "critical",
             "max_tokens": 100, "prefix_groups": 96,
             "deadline_ms": 30000},
            {"name": "ragco", "qps": 2.0, "kind": "rag",
             "criticality": "standard", "max_tokens": 24},
            {"name": "agents", "qps": 0.5, "kind": "agent", "turns": 3,
             "criticality": {"standard": 0.6, "sheddable": 0.4},
             "max_tokens": 16},
        ],
        "diurnal": {"period_s": 120.0, "low": 0.3, "high": 1.0},
        "faults": [
            {"at_s": 30.0, "kind": "zone_kill", "target": "zone-b"},
            {"at_s": 50.0, "kind": "partition",
             "target": "role:decode|role:prefill"},
            {"at_s": 80.0, "kind": "partition_heal",
             "target": "role:decode|role:prefill"},
            {"at_s": 60.0, "kind": "straggler",
             "target": "zone-a-0:8200", "factor": 5.0},
            {"at_s": 60.0, "kind": "straggler",
             "target": "zone-a-1:8200", "factor": 5.0},
        ],
        "breaker_failures": 1,
        "scrape_interval_s": 2.0,
    }
    sim = ClusterSim(Scenario.from_dict(d))
    rep = sim.run()
    assert rep["fleet"]["replicas_peak"] >= 100

    # Zero client-visible stream breaks for the critical class, across
    # the zone kill AND the P↔D cut AND the stragglers.
    crit = rep["classes"]["critical"]
    assert crit["stream_breaks"] == 0
    assert crit["requests"] > 300
    assert crit["no_endpoint"] == 0

    # The incident was actually exercised: the whole of zone-b died and
    # mid-stream failovers happened.
    assert len(rep["fleet"]["dead_ever"]) == 48
    acme = rep["tenants"]["acme"]["critical"]
    assert sum(acme["resumes"].values()) > 0
    assert rep["tenants"]["ragco"]["standard"]["prefill_fallback"] > 0

    # Breaker convergence on EVERY dead endpoint: tripped or
    # scrape-excluded from routing (never silently routable).
    for addr in rep["fleet"]["dead_ever"]:
        converged = (rep["fleet"]["breakers"][addr] != "closed"
                     or not sim.datastore.endpoints[addr].ready)
        assert converged, addr

    # Per-tenant scoreboards with sane percentile ordering.
    for tenant in ("acme", "ragco", "agents"):
        assert tenant in rep["tenants"]
    assert acme["ttft_p99_ms"] >= acme["ttft_p50_ms"] > 0

    # Same seed, byte-identical scoreboard.
    rep2 = ClusterSim(Scenario.from_dict(d)).run()
    assert json.dumps(rep, sort_keys=True) == \
        json.dumps(rep2, sort_keys=True)


@pytest.mark.slow
def test_acceptance_kv_placement_beats_weight3_at_100_replicas():
    """PR 20 acceptance gate: a seeded multi-turn agent trace on a
    104-replica fleet under the round-18 chaos fault timeline (zone
    kill + P↔D partition + stragglers, diurnal load).  The kv-placement
    cost scorer must beat the identical-seed weight-3 baseline on
    steady-state prefix-hit rate AND p99 TTFT / attainment, with zero
    critical stream breaks, and the report must be byte-identical
    across two same-seed runs."""
    def scenario(kv):
        return {
            "name": "kv-fabric", "seed": 1013, "duration_s": 120.0,
            "pd_threshold": 64,
            "replicas": [
                {"zone": "zone-a", "count": 48, "role": "decode",
                 "max_num_seqs": 4},
                {"zone": "zone-b", "count": 48, "role": "decode",
                 "max_num_seqs": 4},
                {"zone": "zone-p", "count": 8, "role": "prefill"},
            ],
            "tenants": [
                {"name": "acme", "qps": 30.0, "criticality": "critical",
                 "max_tokens": 60, "prefix_groups": 24,
                 "prefix_len": 100, "deadline_ms": 30000},
                {"name": "agents", "qps": 6.0, "kind": "agent",
                 "turns": 3, "prefix_groups": 12, "prefix_len": 100,
                 "criticality": "standard", "max_tokens": 16},
            ],
            "diurnal": {"period_s": 120.0, "low": 0.3, "high": 1.0},
            "faults": [
                {"at_s": 30.0, "kind": "zone_kill", "target": "zone-b"},
                {"at_s": 50.0, "kind": "partition",
                 "target": "role:decode|role:prefill"},
                {"at_s": 80.0, "kind": "partition_heal",
                 "target": "role:decode|role:prefill"},
                {"at_s": 60.0, "kind": "straggler",
                 "target": "zone-a-0:8200", "factor": 5.0},
                {"at_s": 60.0, "kind": "straggler",
                 "target": "zone-a-1:8200", "factor": 5.0},
            ],
            "breaker_failures": 1,
            "scrape_interval_s": 1.0,
            "max_inflight": 1024, "max_queue": 2048,
            "kv_placement": kv,
        }

    base = ClusterSim(Scenario.from_dict(scenario(False))).run()
    rep = ClusterSim(Scenario.from_dict(scenario(True))).run()
    assert rep["fleet"]["replicas_peak"] >= 100

    acme = rep["tenants"]["acme"]["critical"]
    base_acme = base["tenants"]["acme"]["critical"]
    # Zero critical stream breaks through the whole incident, both arms.
    assert acme["stream_breaks"] == 0
    assert base_acme["stream_breaks"] == 0
    assert acme["requests"] == base_acme["requests"] > 2000

    # The cost scorer beats weight-3 on BOTH axes: steady-state
    # prefix-hit rate no worse, and the half-fleet-down queueing tail
    # (weight-3 keeps routing at pinned-but-drowning survivors)
    # collapses by an order of magnitude.
    assert acme["prefix_hit_rate"] >= base_acme["prefix_hit_rate"]
    assert acme["ttft_p99_ms"] < base_acme["ttft_p99_ms"] / 2
    assert acme["attainment"] > base_acme["attainment"]
    assert acme["attainment"] > 0.99

    # Placement verdicts cover the tenant's admitted traffic and the
    # multi-turn agent tenant kept its session affinity benefit.
    assert sum(acme["kv_verdicts"].values()) >= acme["requests"]
    agents = rep["tenants"]["agents"]["standard"]
    assert agents["prefix_hit_rate"] >= \
        base["tenants"]["agents"]["standard"]["prefix_hit_rate"]

    # Same seed, byte-identical report (restore sleeps, verdict counts
    # and transfer-byte accounting included).
    rep2 = ClusterSim(Scenario.from_dict(scenario(True))).run()
    assert json.dumps(rep, sort_keys=True) == \
        json.dumps(rep2, sort_keys=True)
