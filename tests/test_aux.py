"""Aux features: top-N logprobs, multistep rollback, layered config.

Round-2 review items: weak #8 (logprobs had no top-N alternatives), weak
#9 (multistep speculative blocks leaked on fallback), aux #32 (no layered
config overlays).
"""

import numpy as np
import pytest

from llm_d_tpu.engine.engine import EngineConfig, EngineCore
from llm_d_tpu.engine.request import Request
from llm_d_tpu.ops.sampling import SamplingParams
from llm_d_tpu.utils.config import deep_merge, load_layers

ENGINE_KW = dict(model="tiny", block_size=4, num_blocks=64, max_num_seqs=8,
                 max_num_batched_tokens=64, min_token_bucket=16,
                 min_seq_bucket=4)


def test_top_logprobs_returned_and_consistent():
    engine = EngineCore(EngineConfig(**ENGINE_KW))
    req = Request(request_id="lp", prompt_token_ids=[5, 6, 7],
                  sampling=SamplingParams(temperature=0.0, max_tokens=3,
                                          ignore_eos=True, logprobs=5))
    engine.add_request(req)
    outs = []
    while engine.has_work():
        outs.extend(engine.step())
    tokens = [t for o in outs for t in o.new_token_ids]
    tops = [t for o in outs for t in (o.top_logprobs or [])]
    chosen = [v for o in outs for v in (o.logprobs or [])]
    assert len(tokens) == len(tops) == len(chosen) == 3
    for tok, top, lp in zip(tokens, tops, chosen):
        assert len(top) == 5
        # Greedy: the chosen token IS the argmax -> best alternative.
        assert tok in top
        assert abs(max(top.values()) - top[tok]) < 1e-5
        assert abs(top[tok] - lp) < 1e-4
        assert all(v <= 0.0 for v in top.values())


def test_multistep_fallback_releases_speculative_blocks():
    """When K-step pre-allocation fails mid-way, earlier requests' tail
    blocks must return to the pool (weak #9: held until finish)."""
    engine = EngineCore(EngineConfig(
        model="tiny", block_size=4, num_blocks=14, max_num_seqs=4,
        max_num_batched_tokens=64, min_token_bucket=16, min_seq_bucket=4,
        num_scheduler_steps=8, enable_prefix_caching=False))
    # Two requests sized so prefill fits but K=8 speculative growth cannot.
    reqs = [Request(request_id=f"m{i}", prompt_token_ids=list(range(1, 20)),
                    sampling=SamplingParams(temperature=0.0, max_tokens=30,
                                            ignore_eos=True))
            for i in range(2)]
    for r in reqs:
        engine.add_request(r)
    baseline_free = None
    for _ in range(200):
        if not engine.has_work():
            break
        engine.step()
        # Invariant after every step: blocks held == blocks the requests'
        # computed tokens need (+ at most the current in-flight growth);
        # speculative K-token tails from failed fusion must not linger.
        held = sum(len(r.block_ids) for r in engine.scheduler.running)
        needed = sum(-(-max(r.num_computed_tokens, 1) // 4) + 2
                     for r in engine.scheduler.running)
        assert held <= needed, (held, needed)
    assert all(len(r.output_token_ids) == 30 for r in reqs)


def test_deep_merge_semantics():
    base = {"a": 1, "b": {"x": 1, "y": 2}, "c": [1, 2]}
    over = {"b": {"y": 3, "z": 4}, "c": [9], "d": True}
    m = deep_merge(base, over)
    assert m == {"a": 1, "b": {"x": 1, "y": 3, "z": 4}, "c": [9], "d": True}
    assert base["b"] == {"x": 1, "y": 2}          # no mutation


def test_layered_config_files(tmp_path):
    (tmp_path / "base.yaml").write_text(
        "model: qwen3-0.6b\nblock-size: 16\nnum-blocks: 1024\n")
    (tmp_path / "tpu.yaml").write_text(
        "num-blocks: 4096\ntensor-parallel-size: 4\n")
    merged = load_layers([str(tmp_path / "base.yaml"),
                          str(tmp_path / "tpu.yaml")])
    assert merged == {"model": "qwen3-0.6b", "block-size": 16,
                      "num-blocks": 4096, "tensor-parallel-size": 4}


def test_config_file_wires_into_server_args(tmp_path):
    import argparse
    from llm_d_tpu.utils.config import apply_file_config

    p = argparse.ArgumentParser()
    p.add_argument("--model", default="tiny")
    p.add_argument("--num-blocks", type=int, default=256)
    p.add_argument("--port", type=int, default=8200)
    argv = ["--port", "9999", "--model", "tiny"]  # explicit, one == default
    args = p.parse_args(argv)
    apply_file_config(args, p, {"model": "llama3-8b", "num-blocks": 4096,
                                "port": 1234}, argv=argv)
    # Explicit flags win even when their value equals the parser default.
    assert args.model == "tiny"
    assert args.num_blocks == 4096
    assert args.port == 9999
    with pytest.raises(ValueError):
        apply_file_config(args, p, {"nonsense-key": 1}, argv=argv)


def test_envvar_lint_gate_passes():
    """The env-var registry linter (scripts/lint-envvars.py) must pass:
    every LLMD_*/LWS_* knob read in code is documented in docs/ENVVARS.md
    and vice versa (reference doctrine: scripts/lint-envvars.py)."""
    import pathlib
    import subprocess
    import sys
    repo = pathlib.Path(__file__).resolve().parent.parent
    proc = subprocess.run(
        [sys.executable, str(repo / "scripts" / "lint-envvars.py")],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_dockerfile_lint_gate_passes():
    """scripts/lint-dockerfile.py (the reference's lint-dockerfile-envvars
    role): shipped Dockerfiles are clean."""
    import pathlib
    import subprocess
    import sys
    repo = pathlib.Path(__file__).resolve().parent.parent
    r = subprocess.run(
        [sys.executable, str(repo / "scripts" / "lint-dockerfile.py")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr


def test_dockerfile_lint_catches_violations(tmp_path, monkeypatch):
    """The linter actually rejects: unregistered env knob, latest tag,
    root user, ADD, apt without cleanup."""
    import importlib.util
    import pathlib
    repo = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "lint_dockerfile", repo / "scripts" / "lint-dockerfile.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    bad = tmp_path / "Dockerfile.bad"
    bad.write_text(
        "FROM python:latest\n"
        "ENV LLMD_NOT_A_REAL_KNOB=1\n"
        "ADD local.tar /app\n"
        "RUN apt-get update && apt-get install -y foo\n"
        "USER root\n")
    errs = mod.lint(bad, {"LLMD_MOE_DISPATCH": "auto"})
    text = "\n".join(errs)
    assert "unpinned base image" in text
    assert "LLMD_NOT_A_REAL_KNOB" in text
    assert "COPY instead of ADD" in text
    assert "apt-get install without" in text
    assert "non-root" in text


def test_v5p256_projection_model():
    """North-star paper model (round-4 verdict #7): documented arithmetic,
    sane bounds, efficiency factor taken from measured rooflines."""
    import bench
    r = bench.project_v5p256(0.5)
    a = r["assumptions"]
    assert 100 < r["projected_v5p256_tok_s_chip"] < 50000
    # DSv3 experts: ~673 GB int8 over 256 chips.
    assert 2.0 < a["expert_gb_per_chip"] < 3.5
    assert a["bound"] in ("ici", "hbm+mxu")
    # Efficiency scales output linearly.
    half = bench.project_v5p256(0.25)["projected_v5p256_tok_s_chip"]
    assert abs(half * 2 - r["projected_v5p256_tok_s_chip"]) < 1.0
