"""Pallas decode-kernel parity vs the jnp reference (interpret mode on CPU).

The kernel under test is the TPU differentiator (FlashInfer role,
reference: docker/Dockerfile.cuda:57-58); bench.py exercises it on real
hardware, these tests pin its numerics on CPU via ``interpret=True`` across
block sizes, GQA ratios, KV widths on both sides of the 128-lane gate, and
the stacked-cache layer addressing — plus the fallback gate itself.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_d_tpu.ops import attention as A
from llm_d_tpu.ops.pallas.paged_attention import paged_attention_decode_update


def _make_decode_case(rng, S, H, KVH, D, block_size, num_blocks, seq_lens,
                      num_layers=None):
    """Random paged cache + one new decode token per sequence."""
    F = KVH * D
    num_slots = num_blocks * block_size
    shape = (num_slots, F) if num_layers is None else (
        num_layers, num_slots, F)
    k_cache = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    v_cache = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    B = max(-(-int(max(seq_lens)) // block_size), 1)
    # Distinct physical blocks per sequence (block 0 is the null block).
    perm = rng.permutation(num_blocks - 1)[: S * B] + 1
    block_tables = jnp.asarray(perm.reshape(S, B), jnp.int32)
    q = jnp.asarray(rng.standard_normal((S, H, D)), jnp.bfloat16)
    k_new = jnp.asarray(rng.standard_normal((S, F)), jnp.bfloat16)
    v_new = jnp.asarray(rng.standard_normal((S, F)), jnp.bfloat16)
    return q, k_new, v_new, k_cache, v_cache, block_tables, \
        jnp.asarray(seq_lens, jnp.int32)


def _reference_decode(q, k_new, v_new, k_cache, v_cache, block_tables,
                      seq_lens, block_size, layer=None):
    """Oracle: scatter the new rows, then full-softmax paged attention."""
    S, H, D = q.shape
    KVH = k_cache.shape[-1] // D
    slot_mapping = (jnp.take_along_axis(
        block_tables, ((seq_lens - 1) // block_size)[:, None], axis=1)[:, 0]
        * block_size + (seq_lens - 1) % block_size)
    k_cache, v_cache = A.write_kv(
        k_cache, v_cache, k_new.reshape(S, KVH, D), v_new.reshape(S, KVH, D),
        slot_mapping, layer=layer)
    out = A.ragged_paged_attention_reference(
        q, k_cache, v_cache,
        token_seq_ids=jnp.arange(S, dtype=jnp.int32),
        positions=seq_lens - 1,
        block_tables=block_tables, seq_lens=seq_lens,
        block_size=block_size, layer=layer)
    return out, k_cache, v_cache


@pytest.mark.parametrize("H,KVH,D,label", [
    (8, 8, 64, "mha-F512"),          # folded width 512 (lane-aligned)
    (8, 2, 64, "gqa4-F128"),         # exactly 128 lanes
    (4, 1, 64, "gqa4-F64-narrow"),   # BELOW the 128-lane gate
    (8, 4, 128, "gqa2-F512-d128"),
])
@pytest.mark.parametrize("block_size", [16, 32])
def test_kernel_matches_reference(H, KVH, D, label, block_size):
    rng = np.random.default_rng(hash((H, KVH, D, block_size)) % 2**32)
    # Lengths exercise: first token, mid-page, exact page boundary, multipage.
    seq_lens = [1, block_size // 2, block_size, block_size + 3,
                3 * block_size]
    S = len(seq_lens)
    case = _make_decode_case(rng, S, H, KVH, D, block_size,
                             num_blocks=S * 3 + 1, seq_lens=seq_lens)
    q, k_new, v_new, k_cache, v_cache, block_tables, lens = case

    out, k_upd, v_upd = paged_attention_decode_update(
        q, k_new, v_new, k_cache, v_cache, block_tables, lens,
        block_size=block_size, num_kv_heads=KVH, interpret=True)
    ref_out, k_ref, v_ref = _reference_decode(
        q, k_new, v_new, k_cache, v_cache, block_tables, lens, block_size)

    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref_out, np.float32),
        atol=2e-2, rtol=2e-2)
    # The fused page write-back must leave the cache exactly as the
    # scatter-then-attend oracle does.
    np.testing.assert_array_equal(
        np.asarray(k_upd, np.float32), np.asarray(k_ref, np.float32))
    np.testing.assert_array_equal(
        np.asarray(v_upd, np.float32), np.asarray(v_ref, np.float32))


@pytest.mark.parametrize("seq_group", [1, 4, 8, 16])
def test_kernel_sequence_grouping(seq_group):
    """Grouped grid programs (G sequences per program) must match the oracle
    with ragged lengths inside a group — including zero-length PAD rows,
    whose clamped page re-reads must neither score nor write back."""
    rng = np.random.default_rng(11 + seq_group)
    H, KVH, D, bs = 8, 2, 64, 16
    real_lens = [1, 7, bs, bs + 1, 2 * bs, 3 * bs - 1, 5, 2]
    S_real = len(real_lens)
    S = 16                                 # 8 real + 8 pad rows
    seq_lens = real_lens + [0] * (S - S_real)
    case = _make_decode_case(rng, S, H, KVH, D, bs, num_blocks=S * 3 + 1,
                             seq_lens=seq_lens)
    q, k_new, v_new, k_cache, v_cache, block_tables, lens = case
    # Pad rows point at the null block, as the engine builds them.
    block_tables = block_tables.at[S_real:].set(0)

    out, k_upd, v_upd = paged_attention_decode_update(
        q, k_new, v_new, k_cache, v_cache, block_tables, lens,
        block_size=bs, num_kv_heads=KVH, interpret=True,
        seq_group=seq_group)
    ref_out, k_ref, v_ref = _reference_decode(
        q[:S_real], k_new[:S_real], v_new[:S_real], k_cache, v_cache,
        block_tables[:S_real], lens[:S_real], bs)

    np.testing.assert_allclose(
        np.asarray(out[:S_real], np.float32),
        np.asarray(ref_out, np.float32), atol=2e-2, rtol=2e-2)
    # Pad rows must not have scattered anything: the caches match an oracle
    # that never saw them.
    np.testing.assert_array_equal(
        np.asarray(k_upd, np.float32), np.asarray(k_ref, np.float32))
    np.testing.assert_array_equal(
        np.asarray(v_upd, np.float32), np.asarray(v_ref, np.float32))


def test_kernel_stacked_cache_layer_addressing():
    """The stacked-cache form must touch ONLY the addressed layer plane."""
    rng = np.random.default_rng(7)
    H, KVH, D, bs, L = 8, 2, 64, 16, 3
    seq_lens = [5, 2 * bs + 1]
    S = len(seq_lens)
    case = _make_decode_case(rng, S, H, KVH, D, bs, num_blocks=8,
                             seq_lens=seq_lens, num_layers=L)
    q, k_new, v_new, k_cache, v_cache, block_tables, lens = case
    layer = jnp.asarray(1, jnp.int32)

    out, k_upd, v_upd = paged_attention_decode_update(
        q, k_new, v_new, k_cache, v_cache, block_tables, lens,
        block_size=bs, num_kv_heads=KVH, layer=layer, interpret=True)
    ref_out, k_ref, v_ref = _reference_decode(
        q, k_new, v_new, k_cache, v_cache, block_tables, lens, bs,
        layer=layer)

    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref_out, np.float32),
        atol=2e-2, rtol=2e-2)
    np.testing.assert_array_equal(
        np.asarray(k_upd, np.float32), np.asarray(k_ref, np.float32))
    # Planes 0 and 2 are untouched by construction of the oracle; assert the
    # kernel's write-back honored the same invariant.
    np.testing.assert_array_equal(
        np.asarray(k_upd[0], np.float32), np.asarray(k_cache[0], np.float32))
    np.testing.assert_array_equal(
        np.asarray(v_upd[2], np.float32), np.asarray(v_cache[2], np.float32))


def _decode_batch(S, T, block_tables, seq_lens):
    """Engine-shaped ragged decode batch (Q == 1) for the dispatch entry."""
    return dict(
        token_seq_ids=jnp.arange(S, dtype=jnp.int32),
        positions=seq_lens - 1,
        slot_mapping=(jnp.take_along_axis(
            block_tables,
            ((seq_lens - 1) // 16)[:, None], axis=1)[:, 0] * 16
            + (seq_lens - 1) % 16),
        block_tables=block_tables,
        seq_lens=seq_lens,
        qtok_idx=jnp.arange(S, dtype=jnp.int32)[:, None],
        token_qpos=jnp.zeros(S, jnp.int32),
    )


def test_lane_gate_falls_back_without_kernel():
    """KVH*D % 128 != 0 with backend='pallas' must take the chunked path.

    Running on CPU proves the fallback fired: the real Mosaic kernel cannot
    execute here, so a correct result means the gate routed around it.
    """
    rng = np.random.default_rng(3)
    H, KVH, D, bs = 4, 1, 64, 16          # F = 64 -> below the lane gate
    seq_lens = [9, 17]
    S = len(seq_lens)
    q, k_new, v_new, k_cache, v_cache, block_tables, lens = _make_decode_case(
        rng, S, H, KVH, D, bs, num_blocks=8, seq_lens=seq_lens)
    batch = _decode_batch(S, S, block_tables, lens)
    out, k_upd, v_upd = A.attention_with_kv_update(
        q, k_new.reshape(S, KVH, D), v_new.reshape(S, KVH, D),
        k_cache, v_cache, batch, block_size=bs, backend="pallas")
    ref_out, k_ref, v_ref = _reference_decode(
        q, k_new, v_new, k_cache, v_cache, block_tables, lens, bs)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref_out, np.float32),
        atol=2e-2, rtol=2e-2)
    np.testing.assert_array_equal(
        np.asarray(k_upd, np.float32), np.asarray(k_ref, np.float32))


def test_block_size_gate_falls_back_without_kernel():
    """block_size % 16 != 0 (bf16 sublane tiling) must also fall back."""
    rng = np.random.default_rng(4)
    H, KVH, D, bs = 8, 2, 64, 8           # F = 128 aligned, bs too small
    seq_lens = [3, 11]
    S = len(seq_lens)
    q, k_new, v_new, k_cache, v_cache, block_tables, lens = _make_decode_case(
        rng, S, H, KVH, D, bs, num_blocks=8, seq_lens=seq_lens)
    batch = dict(
        token_seq_ids=jnp.arange(S, dtype=jnp.int32),
        positions=lens - 1,
        slot_mapping=(jnp.take_along_axis(
            block_tables, ((lens - 1) // bs)[:, None], axis=1)[:, 0] * bs
            + (lens - 1) % bs),
        block_tables=block_tables, seq_lens=lens,
        qtok_idx=jnp.arange(S, dtype=jnp.int32)[:, None],
        token_qpos=jnp.zeros(S, jnp.int32))
    out, _, _ = A.attention_with_kv_update(
        q, k_new.reshape(S, KVH, D), v_new.reshape(S, KVH, D),
        k_cache, v_cache, batch, block_size=bs, backend="pallas")
    ref_out, _, _ = _reference_decode(
        q, k_new, v_new, k_cache, v_cache, block_tables, lens, bs)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref_out, np.float32),
        atol=2e-2, rtol=2e-2)
