"""Int8 paged-KV cache contract (kv_cache_dtype=int8), end to end.

The claim under test is the ISSUE-5 acceptance set: quantized decode and
prefill attention match bf16 within an explicit error bound (kernel AND
XLA-fallback numerics are the same dequantize-then-attend), the dtype-aware
block pool is >= 1.9x the bf16 pool at a fixed HBM budget, the offload tier
round-trips scale planes byte-exactly, the P->D wire ships ~half the bf16
bytes and REJECTS dtype/version mismatches, and a whole engine generates
deterministically on the int8 cache.  Everything runs on CPU: Pallas via
``interpret=True``, engine paths via the XLA fallback (same numerics).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from llm_d_tpu.engine.engine import (
    EngineConfig, EngineCore, derive_num_blocks, kv_block_bytes)
from llm_d_tpu.engine.request import Request
from llm_d_tpu.ops import attention as A
from llm_d_tpu.ops.pallas.flash_prefill import flash_prefill_paged
from llm_d_tpu.ops.pallas.paged_attention import paged_attention_decode_update
from llm_d_tpu.ops.quant import (
    dequantize_kv_block, kv_scale_width, quantize_kv_block)
from llm_d_tpu.ops.sampling import SamplingParams
from llm_d_tpu.transfer.connector import (
    _pack_blocks, _scatter_blocks, _WIRE_VERSION, _HEADER, _MAGIC)

# Quantization of ~N(0,1) rows: per-element error <= amax/254 (~0.016 at
# amax ~4); through softmax-weighted sums the attention output lands well
# inside this band.  The bound is the TESTED contract the docs quote.
ATOL_VS_BF16 = 8e-2


def greedy_req(rid, prompt, n=4, **kw):
    return Request(request_id=rid, prompt_token_ids=list(prompt),
                   sampling=SamplingParams(temperature=0.0, max_tokens=n,
                                           ignore_eos=True), **kw)


ENGINE_KW = dict(model="tiny", block_size=4, num_blocks=32, max_num_seqs=4,
                 max_num_batched_tokens=64, min_token_bucket=16,
                 min_seq_bucket=4)


# ---------------------------------------------------------------------------
# quantize/dequantize primitives
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sw", [1, 4])
def test_quantize_roundtrip_error_bound(sw):
    rng = np.random.default_rng(sw)
    rows = jnp.asarray(rng.standard_normal((3, 9, 256)), jnp.float32)
    q, s = quantize_kv_block(rows, sw)
    assert q.dtype == jnp.int8 and s.shape == (3, 9, sw)
    back = np.asarray(dequantize_kv_block(q, s, jnp.float32))
    # Symmetric int8: per-element error <= scale/2 of its column group.
    bound = np.repeat(np.asarray(s) / 2, 256 // sw, axis=-1) + 1e-6
    assert (np.abs(back - np.asarray(rows)) <= bound).all()


def test_scale_width_granularities():
    assert kv_scale_width(8, "token") == 1
    assert kv_scale_width(8, "head") == 8


# ---------------------------------------------------------------------------
# Pallas decode kernel parity (interpret mode)
# ---------------------------------------------------------------------------

def _decode_case(rng, S, H, KVH, D, bs, num_blocks, seq_lens, L=None):
    F = KVH * D
    num_slots = num_blocks * bs
    shape = (num_slots, F) if L is None else (L, num_slots, F)
    k_cache = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    v_cache = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    B = max(-(-int(max(seq_lens)) // bs), 1)
    perm = rng.permutation(num_blocks - 1)[: S * B] + 1
    bt = jnp.asarray(perm.reshape(S, B), jnp.int32)
    q = jnp.asarray(rng.standard_normal((S, H, D)), jnp.bfloat16)
    k_new = jnp.asarray(rng.standard_normal((S, F)), jnp.bfloat16)
    v_new = jnp.asarray(rng.standard_normal((S, F)), jnp.bfloat16)
    return q, k_new, v_new, k_cache, v_cache, bt, \
        jnp.asarray(seq_lens, jnp.int32)


def _bf16_decode_oracle(q, k_new, v_new, k_cache, v_cache, bt, lens, bs,
                        layer=None):
    S, H, D = q.shape
    KVH = k_cache.shape[-1] // D
    slot_mapping = (jnp.take_along_axis(
        bt, ((lens - 1) // bs)[:, None], axis=1)[:, 0]
        * bs + (lens - 1) % bs)
    k_cache, v_cache = A.write_kv(
        k_cache, v_cache, k_new.reshape(S, KVH, D), v_new.reshape(S, KVH, D),
        slot_mapping, layer=layer)
    out = A.ragged_paged_attention_reference(
        q, k_cache, v_cache, jnp.arange(S, dtype=jnp.int32), lens - 1,
        bt, lens, block_size=bs, layer=layer)
    return out, slot_mapping


@pytest.mark.parametrize("sw_name", ["token", "head"])
def test_decode_kernel_int8_parity(sw_name):
    """The quantized kernel must (a) EXACTLY match the dequantize-then-
    attend oracle built from the same int8 cache — kernel and XLA fallback
    implement identical numerics — and (b) match the pure-bf16 attention
    within the quoted quantization bound."""
    rng = np.random.default_rng(7)
    H, KVH, D, bs, L = 8, 2, 64, 32, 3
    seq_lens = [1, bs // 2, bs, bs + 3, 3 * bs]
    S = len(seq_lens)
    q, k_new, v_new, k_bf, v_bf, bt, lens = _decode_case(
        rng, S, H, KVH, D, bs, num_blocks=S * 3 + 1, seq_lens=seq_lens, L=L)
    layer = jnp.asarray(1, jnp.int32)
    sw = kv_scale_width(KVH, sw_name)

    kq, ks = quantize_kv_block(k_bf, sw)
    vq, vs = quantize_kv_block(v_bf, sw)
    knq, kns = quantize_kv_block(k_new, sw)
    vnq, vns = quantize_kv_block(v_new, sw)

    out, k_u, v_u, ks_u, vs_u = paged_attention_decode_update(
        q, knq, vnq, kq, vq, bt, lens, block_size=bs, num_kv_heads=KVH,
        layer=layer, interpret=True,
        k_scale=ks, v_scale=vs, k_scale_new=kns, v_scale_new=vns)

    # (a) vs the dequantized-int8 oracle: bf16-rounding-level agreement.
    ref_q, slot_mapping = _bf16_decode_oracle(
        q, dequantize_kv_block(knq, kns), dequantize_kv_block(vnq, vns),
        dequantize_kv_block(kq, ks), dequantize_kv_block(vq, vs),
        bt, lens, bs, layer=layer)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref_q, np.float32),
        atol=2e-2, rtol=2e-2)
    # (b) vs pure bf16: the quantization bound the docs quote.
    ref_bf, _ = _bf16_decode_oracle(
        q, k_new, v_new, k_bf, v_bf, bt, lens, bs, layer=layer)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref_bf, np.float32),
        atol=ATOL_VS_BF16, rtol=ATOL_VS_BF16)

    # Page + scale write-back byte-exact: the new int8 row and its f32
    # scale land where the scatter oracle puts them, nothing else moves.
    np.testing.assert_array_equal(
        np.asarray(k_u), np.asarray(kq.at[layer, slot_mapping].set(knq)))
    np.testing.assert_array_equal(
        np.asarray(ks_u), np.asarray(ks.at[layer, slot_mapping].set(kns)))
    np.testing.assert_array_equal(
        np.asarray(vs_u), np.asarray(vs.at[layer, slot_mapping].set(vns)))
    # Untouched layer planes stay untouched.
    np.testing.assert_array_equal(np.asarray(k_u[0]), np.asarray(kq[0]))
    np.testing.assert_array_equal(np.asarray(vs_u[2]), np.asarray(vs[2]))


# ---------------------------------------------------------------------------
# Pallas prefill kernel parity (interpret mode)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sw", [1, 2])
def test_prefill_kernel_int8_parity(sw):
    rng = np.random.default_rng(11)
    S, Q, H, KVH, D, bs, L = 3, 8, 8, 2, 64, 32, 2
    F = KVH * D
    num_blocks, B = 12, 3
    seq_lens = np.array([5, 40, 96], np.int32)
    k_bf = jnp.asarray(rng.standard_normal((L, num_blocks * bs, F)),
                       jnp.bfloat16)
    v_bf = jnp.asarray(rng.standard_normal((L, num_blocks * bs, F)),
                       jnp.bfloat16)
    perm = rng.permutation(num_blocks - 1)[: S * B] + 1
    bt = jnp.asarray(perm.reshape(S, B), jnp.int32)
    lens = jnp.asarray(seq_lens)
    layer = jnp.asarray(1, jnp.int32)
    qs = jnp.asarray(rng.standard_normal((S, Q, H, D)), jnp.bfloat16)
    q_pos = jnp.asarray(np.stack(
        [np.clip(np.arange(Q) + l - Q, -1, None) for l in seq_lens]),
        jnp.int32)

    kq, ks = quantize_kv_block(k_bf, sw)
    vq, vs = quantize_kv_block(v_bf, sw)
    out = flash_prefill_paged(
        qs, q_pos, kq, vq, bt, lens, block_size=bs, num_kv_heads=KVH,
        layer=layer, interpret=True, k_scale=ks, v_scale=vs)
    # Same-numerics oracle: the bf16 kernel over the dequantized cache.
    ref_q = flash_prefill_paged(
        qs, q_pos, dequantize_kv_block(kq, ks), dequantize_kv_block(vq, vs),
        bt, lens, block_size=bs, num_kv_heads=KVH, layer=layer,
        interpret=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref_q, np.float32),
        atol=2e-2, rtol=2e-2)
    ref_bf = flash_prefill_paged(
        qs, q_pos, k_bf, v_bf, bt, lens, block_size=bs, num_kv_heads=KVH,
        layer=layer, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref_bf, np.float32),
        atol=ATOL_VS_BF16, rtol=ATOL_VS_BF16)


# ---------------------------------------------------------------------------
# XLA fallback: decode + prefill-append through attention_with_kv_update
# ---------------------------------------------------------------------------

def _decode_batch(S, bt, lens, bs):
    return dict(
        token_seq_ids=jnp.arange(S, dtype=jnp.int32),
        positions=lens - 1,
        slot_mapping=(jnp.take_along_axis(
            bt, ((lens - 1) // bs)[:, None], axis=1)[:, 0] * bs
            + (lens - 1) % bs),
        block_tables=bt, seq_lens=lens,
        qtok_idx=jnp.arange(S, dtype=jnp.int32)[:, None],
        token_qpos=jnp.zeros(S, jnp.int32))


@pytest.mark.parametrize("backend", ["chunked", "reference"])
def test_xla_fallback_decode_parity_and_scale_writes(backend):
    rng = np.random.default_rng(13)
    H, KVH, D, bs = 8, 2, 64, 16
    seq_lens = [3, 20, 33]
    S = len(seq_lens)
    q, k_new, v_new, k_bf, v_bf, bt, lens = _decode_case(
        rng, S, H, KVH, D, bs, num_blocks=10, seq_lens=seq_lens)
    kq, ks = quantize_kv_block(k_bf, 1)
    vq, vs = quantize_kv_block(v_bf, 1)
    batch = _decode_batch(S, bt, lens, bs)
    out, k_u, v_u, ks_u, vs_u = A.attention_with_kv_update(
        q, k_new.reshape(S, KVH, D), v_new.reshape(S, KVH, D), kq, vq,
        batch, block_size=bs, backend=backend, k_scale=ks, v_scale=vs)
    ref, _ = _bf16_decode_oracle(q, k_new, v_new, k_bf, v_bf, bt, lens, bs)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=ATOL_VS_BF16, rtol=ATOL_VS_BF16)
    # The new rows' scales were scattered next to the payload.
    sm = np.asarray(batch["slot_mapping"])
    knq, kns = quantize_kv_block(k_new, 1)
    np.testing.assert_array_equal(np.asarray(ks_u)[sm], np.asarray(kns))
    np.testing.assert_array_equal(np.asarray(k_u)[sm], np.asarray(knq))


def test_prefill_append_fallback_quantizes_new_rows():
    """Prefill (Q > 1) through the chunked fallback on an int8 cache:
    freshly appended rows are quantized + scales written, and attention
    over them matches bf16 within the bound."""
    rng = np.random.default_rng(17)
    H, KVH, D, bs = 4, 2, 64, 16
    S, Q = 2, 4
    T = S * Q
    F = KVH * D
    num_blocks = 8
    k_bf = jnp.zeros((num_blocks * bs, F), jnp.bfloat16)
    v_bf = jnp.zeros((num_blocks * bs, F), jnp.bfloat16)
    kq, ks = quantize_kv_block(k_bf, 1)
    vq, vs = quantize_kv_block(v_bf, 1)
    bt = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    lens = jnp.asarray([Q, Q], jnp.int32)
    positions = jnp.asarray([0, 1, 2, 3, 0, 1, 2, 3], jnp.int32)
    seq_ids = jnp.asarray([0, 0, 0, 0, 1, 1, 1, 1], jnp.int32)
    slot_mapping = (jnp.repeat(bt[:, 0], Q) * bs
                    + jnp.tile(jnp.arange(Q), S)).astype(jnp.int32)
    batch = dict(
        token_seq_ids=seq_ids, positions=positions,
        slot_mapping=slot_mapping, block_tables=bt, seq_lens=lens,
        qtok_idx=jnp.arange(T, dtype=jnp.int32).reshape(S, Q),
        token_qpos=jnp.tile(jnp.arange(Q), S).astype(jnp.int32))
    q = jnp.asarray(rng.standard_normal((T, H, D)), jnp.bfloat16)
    k_new = jnp.asarray(rng.standard_normal((T, KVH, D)), jnp.bfloat16)
    v_new = jnp.asarray(rng.standard_normal((T, KVH, D)), jnp.bfloat16)

    out, k_u, v_u, ks_u, vs_u = A.attention_with_kv_update(
        q, k_new, v_new, kq, vq, batch, block_size=bs, backend="chunked",
        k_scale=ks, v_scale=vs)
    ref, _, _ = A.attention_with_kv_update(
        q, k_new, v_new, k_bf, v_bf, batch, block_size=bs,
        backend="chunked")
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=ATOL_VS_BF16, rtol=ATOL_VS_BF16)
    knq, kns = quantize_kv_block(k_new.reshape(T, F), 1)
    sm = np.asarray(slot_mapping)
    np.testing.assert_array_equal(np.asarray(k_u)[sm], np.asarray(knq))
    np.testing.assert_array_equal(np.asarray(ks_u)[sm], np.asarray(kns))


# ---------------------------------------------------------------------------
# Block-pool sizing (capacity half of the win)
# ---------------------------------------------------------------------------

def test_block_pool_at_least_1p9x_at_same_budget():
    layout = {"k": 512, "v": 512}          # llama3-1b folded widths
    budget = 4 << 30
    bf16 = derive_num_blocks(budget, layout, 16, 64, "bf16")
    int8 = derive_num_blocks(budget, layout, 16, 64, "int8", 1)
    assert int8 / bf16 >= 1.9, (bf16, int8)
    # Byte accounting is exact: payload/2 + scale overhead.
    assert kv_block_bytes(layout, 16, 64, "int8", 1) \
        == 16 * 64 * (1024 + 2 * 4)


def test_engine_auto_sizes_pool_dtype_aware():
    budget = 1 << 20
    kw = dict(model="tiny", block_size=4, max_num_seqs=4,
              max_num_batched_tokens=64, min_token_bucket=16,
              min_seq_bucket=4, kv_cache_hbm_bytes=budget)
    bf = EngineCore(EngineConfig(**kw))
    q8 = EngineCore(EngineConfig(**kw, kv_cache_dtype="int8"))
    assert q8.config.num_blocks > 1.5 * bf.config.num_blocks
    assert q8.kv_manager.num_blocks == q8.config.num_blocks


# ---------------------------------------------------------------------------
# Engine e2e on the int8 cache
# ---------------------------------------------------------------------------

def test_engine_e2e_int8_generates_deterministically():
    bf = EngineCore(EngineConfig(**ENGINE_KW))
    q8a = EngineCore(EngineConfig(**ENGINE_KW, kv_cache_dtype="int8"),
                     params=bf.params)
    q8b = EngineCore(EngineConfig(**ENGINE_KW, kv_cache_dtype="int8"),
                     params=bf.params)
    assert q8a.kv_cache["k"].dtype == jnp.int8
    assert q8a.kv_cache["k_scale"].dtype == jnp.float32
    prompt = [7, 3, 9, 1, 4, 6, 2, 8, 5, 0, 11, 13]
    a = q8a.generate([greedy_req("a", prompt, 6)])["a"]
    b = q8b.generate([greedy_req("b", prompt, 6)])["b"]
    assert len(a) == 6 and a == b, (a, b)


def test_engine_int8_mla_builds_latent_cache():
    """The PR-5 rejection is lifted (round 9): int8 + MLA builds the int8
    latent buffer with its per-row scale plane (full contract coverage
    lives in tests/test_mla_quant.py)."""
    e = EngineCore(EngineConfig(model="tiny-mla", kv_cache_dtype="int8"))
    assert e.kv_cache["kv"].dtype == jnp.int8
    assert e.kv_cache["kv_scale"].dtype == jnp.float32
    assert e.kv_scale_width == 1           # one symmetric scale per row


def test_engine_rejects_unknown_dtype_and_granularity():
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        EngineCore(EngineConfig(**ENGINE_KW, kv_cache_dtype="fp4"))
    with pytest.raises(ValueError, match="granularity"):
        EngineCore(EngineConfig(**ENGINE_KW, kv_cache_dtype="int8",
                                kv_scale_granularity="block"))


def test_env_knobs_with_invalid_value_fallback(monkeypatch):
    monkeypatch.setenv("LLMD_KV_CACHE_DTYPE", "banana")
    e = EngineCore(EngineConfig(**ENGINE_KW))
    assert e.kv_cache_dtype == "bf16"          # invalid env degrades
    monkeypatch.setenv("LLMD_KV_CACHE_DTYPE", "int8")
    monkeypatch.setenv("LLMD_KV_SCALE_GRAN", "head")
    e = EngineCore(EngineConfig(**ENGINE_KW))
    assert e.kv_cache_dtype == "int8"
    assert e.kv_scale_width == e.model_config.num_kv_heads
    assert e.kv_cache["k_scale"].shape[-1] == e.kv_scale_width


# ---------------------------------------------------------------------------
# Offload tier: int8 blocks + scales round-trip
# ---------------------------------------------------------------------------

def test_offload_restore_int8_byte_exact_scales():
    """Device-evicted int8 blocks restore from the host tier with their
    scale planes byte-exact, and the restored prefix decodes identically."""
    engine = EngineCore(EngineConfig(
        model="tiny", block_size=4, num_blocks=16, max_num_seqs=4,
        max_num_batched_tokens=64, min_token_bucket=16, min_seq_bucket=4,
        kv_offload_blocks=64, kv_cache_dtype="int8"))
    prompt = [7, 3, 9, 1, 4, 6, 2, 8, 5, 0, 11, 13]
    first = engine.generate([greedy_req("a1", prompt, 4)])["a1"]
    assert engine.host_tier.saves >= 3
    # The packed slab round-trips every buffer (int8 payloads + f32
    # scales) byte-exactly.
    from llm_d_tpu.engine.offload import (
        _pack_block_slab, _slab_layout, _unpack_block_slab)
    blob = next(iter(engine.host_tier._store.values()))
    L = engine.model_config.num_layers
    slab = _unpack_block_slab(blob, _slab_layout(engine), L, 4)
    assert slab["k"].dtype == np.int8
    assert slab["k_scale"].dtype == np.float32
    assert _pack_block_slab(slab) == blob      # byte-exact round trip

    for i in range(6):
        filler = [(100 + 17 * i + j) % 500 for j in range(12)]
        engine.generate([greedy_req(f"f{i}", filler, 2)])
    assert engine.kv_manager.eviction_count > 0
    r2 = greedy_req("a2", prompt, 4)
    second = engine.generate([r2])["a2"]
    assert second == first
    assert engine.host_tier.loads > 0
    assert r2.num_cached_prompt_tokens >= 8


def test_offload_slab_rejects_dtype_mismatch():
    """A bf16 pod must reject an int8 peer's slab (and vice versa) rather
    than reinterpret it — kv_cache_dtype is part of the tier contract."""
    from llm_d_tpu.engine.offload import _slab_layout, _unpack_block_slab
    q8 = EngineCore(EngineConfig(**ENGINE_KW, kv_cache_dtype="int8",
                                 kv_offload_blocks=8))
    bf = EngineCore(EngineConfig(**ENGINE_KW, kv_offload_blocks=8))
    q8.generate([greedy_req("x", [1, 2, 3, 4, 5, 6, 7, 8], 2)])
    blob = next(iter(q8.host_tier._store.values()))
    L = q8.model_config.num_layers
    with pytest.raises(ValueError):
        _unpack_block_slab(blob, _slab_layout(bf), L, 4)


# ---------------------------------------------------------------------------
# P->D wire: halved payload, versioned header, dtype rejection
# ---------------------------------------------------------------------------

def test_transfer_wire_int8_half_bytes_and_rejection():
    bf = EngineCore(EngineConfig(**ENGINE_KW))
    q8 = EngineCore(EngineConfig(**ENGINE_KW, kv_cache_dtype="int8"),
                    params=bf.params)
    q8b = EngineCore(EngineConfig(**ENGINE_KW, kv_cache_dtype="int8"),
                     params=bf.params)
    prompt = [7, 3, 9, 1, 4, 6, 2, 8]
    q8.generate([greedy_req("a", prompt, 2)])
    bf.generate([greedy_req("a", prompt, 2)])
    blocks = [1, 2]
    blob8 = _pack_blocks(q8, blocks)
    blob16 = _pack_blocks(bf, blocks)
    # ~Half the bytes (scale planes + headers keep it just above 0.5; the
    # tiny model's narrow 32-wide rows make the overhead visible — real
    # widths land at ~0.51).
    assert len(blob8) < 0.65 * len(blob16), (len(blob8), len(blob16))

    # int8 -> int8: scatter lands payload AND scales byte-exactly.
    _scatter_blocks(q8b, blocks, blob8)
    slots = slice(blocks[0] * 4, (blocks[-1] + 1) * 4)
    for name in q8.kv_cache:
        np.testing.assert_array_equal(
            np.asarray(q8.kv_cache[name][:, slots]),
            np.asarray(q8b.kv_cache[name][:, slots]), err_msg=name)

    # int8 -> bf16 consumer: rejected (buffer set differs), never
    # reinterpreted.
    with pytest.raises(ValueError):
        _scatter_blocks(bf, blocks, blob8)
    # bf16 -> int8 consumer: also rejected.
    with pytest.raises(ValueError):
        _scatter_blocks(q8b, blocks, blob16)

    # Version tampering is a named error, not a misparse.
    tampered = bytearray(blob8)
    hdr = list(_HEADER.unpack_from(bytes(tampered), 0))
    assert hdr[0] == _MAGIC and hdr[1] == _WIRE_VERSION
    hdr[1] = _WIRE_VERSION + 1
    tampered[:_HEADER.size] = _HEADER.pack(*hdr)
    with pytest.raises(ValueError, match="version"):
        _scatter_blocks(q8b, blocks, bytes(tampered))

    # Dtype-code tampering on a structurally valid slab: named rejection.
    tampered = bytearray(blob8)
    # First buffer segment header sits right after the slab header.
    import struct
    width, code = struct.unpack_from("<IB", bytes(tampered), _HEADER.size)
    struct.pack_into("<IB", tampered, _HEADER.size, width,
                     0 if code != 0 else 1)
    with pytest.raises(ValueError, match="dtype|shipped"):
        _scatter_blocks(q8b, blocks, bytes(tampered))


def test_pd_e2e_int8_parity():
    """Producer -> consumer over the real connector with int8 caches on
    both sides: the pulled prefix decodes exactly like a local int8 run."""
    from llm_d_tpu.transfer.connector import KVConnectorConfig, TpuConnector
    from llm_d_tpu.engine.request import RequestState
    import time
    kw = dict(ENGINE_KW, kv_cache_dtype="int8")
    baseline = EngineCore(EngineConfig(**kw))
    producer = EngineCore(EngineConfig(**kw), params=baseline.params)
    producer.kv_connector = TpuConnector(
        KVConnectorConfig(kv_role="kv_producer", host="127.0.0.1"))
    consumer = EngineCore(EngineConfig(**kw), params=baseline.params)
    consumer.kv_connector = TpuConnector(
        KVConnectorConfig(kv_role="kv_consumer", timeout_ms=5000))
    try:
        prompt = [3, 1, 4, 1, 5, 9, 2, 6]
        want = baseline.generate([greedy_req("b", prompt, 4)])["b"]
        preq = greedy_req("pd", prompt, 1, do_remote_decode=True)
        producer.add_request(preq)
        for _ in range(500):
            producer.step()
            if preq.state == RequestState.FINISHED_REMOTE_PREFILL:
                break
            time.sleep(0.001)
        assert preq.state == RequestState.FINISHED_REMOTE_PREFILL
        dreq = greedy_req("pd", prompt, 4, do_remote_prefill=True,
                          kv_transfer_params=preq.kv_transfer_params)
        got = consumer.generate([dreq])["pd"]
        assert got == want, (got, want)
    finally:
        producer.kv_connector.close()
        consumer.kv_connector.close()
