"""Quantized EP/TP collective contract (LLMD_COLLECTIVE_DTYPE), end to end.

The claim under test is the ISSUE-8 acceptance set: the int8 exchange
wire (per-row symmetric int8 payloads + f32 scale vectors riding sibling
exchanges — parallel/quant_collectives.py) matches the bf16 wire within
2% rel-RMS PER COLLECTIVE (dispatch and combine bounded separately), the
scale plane lands exactly aligned with its payload rows under skewed
routing and chunking (byte-exact round trip on exactly-representable
rows), the EQuARX-style quantized allreduce matches ``lax.psum`` on both
the flattened EP axes and a single TP axis, the accuracy harness holds
its documented bounds on REAL routed traces (the gate behind ``auto``),
the env knob rejects unsupported dtypes by falling back, and the engine
exports the wire-byte accounting.  Everything runs on CPU: the dense
``all_to_all`` fallback ships the identical quantized payloads the TPU
ragged path does (quantization happens before the exchange, per row, so
both branches deliver the same bytes), and the int8 EXPERT kernel rides
along in interpret mode to prove the quantized wire feeds the streamed
kernel path unchanged.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from llm_d_tpu.models.config import ModelConfig
from llm_d_tpu.ops import collective_accuracy as acc
from llm_d_tpu.ops import moe as moe_ops
from llm_d_tpu.ops.quant import dequantize, quantize_int8
from llm_d_tpu.parallel.mesh import AXIS_EP, MeshConfig, make_mesh
from llm_d_tpu.parallel.quant_collectives import (
    a2a_row_bytes,
    dequantize_rows,
    ep_a2a_bytes_per_token,
    quantize_rows,
    quantized_psum,
    resolve_collective_dtype,
)
from llm_d_tpu.utils.jax_compat import shard_map


@pytest.fixture(scope="module")
def mesh(devices):
    return make_mesh(MeshConfig(dp=4, sp=1, tp=2), devices)


def _case(seed, T, E, H=32, I=16, k=2):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((T, H)), jnp.bfloat16)
    router = jnp.asarray(rng.standard_normal((H, E)), jnp.float32)
    w_gate = jnp.asarray(rng.standard_normal((E, H, I)) * 0.2, jnp.bfloat16)
    w_up = jnp.asarray(rng.standard_normal((E, H, I)) * 0.2, jnp.bfloat16)
    w_down = jnp.asarray(rng.standard_normal((E, I, H)) * 0.2, jnp.bfloat16)
    cfg = ModelConfig(name="cq-test", num_experts=E, num_experts_per_tok=k,
                      moe_renormalize=True)
    weights, idx = moe_ops.route(
        jnp.dot(x.astype(jnp.float32), router), cfg)
    return x, weights, idx, w_gate, w_up, w_down


def _rel_rms(a, b, ref):
    a, b, ref = (np.asarray(v, np.float32) for v in (a, b, ref))
    return float(np.sqrt(np.mean((a - b) ** 2))
                 / max(np.sqrt(np.mean(ref ** 2)), 1e-12))


# ---------------------------------------------------------------------------
# Wire-mode parity (the 2% rel-RMS per-collective acceptance bound)
# ---------------------------------------------------------------------------

def test_int8_wire_parity_per_collective(mesh):
    """Each collective's quantization error, isolated by differencing
    wire modes against the SAME routing, is bounded at 2% rel-RMS of the
    oracle output — the acceptance bound, asserted on the op itself."""
    x, w, idx, wg, wu, wd = _case(7, 32, 16)
    oracle = moe_ops.expert_ffn(x, w, idx, wg, wu, wd, mesh=mesh,
                                dispatch="psum")
    outs = {mode: moe_ops.expert_ffn_a2a(
        x, w, idx, wg, wu, wd, mesh, collective_dtype=mode)
        for mode in ("bf16", "int8-dispatch", "int8")}
    # Dispatch collective: int8 outbound vs bf16 outbound, same combine.
    assert _rel_rms(outs["int8-dispatch"], outs["bf16"], oracle) <= 2e-2
    # Combine collective: int8 return vs bf16 return, same dispatch.
    assert _rel_rms(outs["int8"], outs["int8-dispatch"], oracle) <= 2e-2
    # And the full int8 wire still lands on the oracle.
    np.testing.assert_allclose(np.asarray(outs["int8"], np.float32),
                               np.asarray(oracle, np.float32),
                               atol=6e-2, rtol=6e-2)


def test_bf16_combine_downcast_parity(mesh):
    """The round-10 quick win: the bf16 baseline combine no longer ships
    f32 rows.  Parity vs the psum oracle pins the downcast's tolerance —
    one bf16 rounding of the expert output, inside the pre-existing
    dispatch tolerance."""
    x, w, idx, wg, wu, wd = _case(11, 16, 8)
    oracle = moe_ops.expert_ffn(x, w, idx, wg, wu, wd, mesh=mesh,
                                dispatch="psum")
    a2a = moe_ops.expert_ffn_a2a(x, w, idx, wg, wu, wd, mesh,
                                 collective_dtype="bf16")
    np.testing.assert_allclose(np.asarray(a2a, np.float32),
                               np.asarray(oracle, np.float32),
                               atol=3e-2, rtol=3e-2)


def test_int8_wire_feeds_streamed_kernel_interpret(mesh):
    """Quantized wire + quantized EXPERTS together: the dequantized
    arrival rows feed the chunk-streamed int8 kernel (interpret mode)
    exactly like bf16 arrivals do — the wide-EP serving configuration,
    end to end on CPU."""
    key = jax.random.PRNGKey(3)
    T, E, H, I, k = 32, 16, 64, 32, 2
    ks = jax.random.split(key, 4)
    x = jax.random.normal(ks[0], (T, H), jnp.bfloat16)
    idx = jax.random.randint(ks[1], (T, k), 0, E)
    w = jnp.abs(jax.random.normal(ks[2], (T, k), jnp.float32)) * 0.3
    stack = lambda a: jnp.stack([jnp.zeros_like(a), a])
    quant = {"layer": jnp.int32(1)}
    deq = []
    wkeys = jax.random.split(ks[3], 3)
    for (name, shape), kk in zip(
            (("w_gate", (E, H, I)), ("w_up", (E, H, I)),
             ("w_down", (E, I, H))), wkeys):
        q, s = quantize_int8(
            jax.random.normal(kk, shape, jnp.float32) * 0.05)
        quant[f"{name}_q"], quant[f"{name}_s"] = stack(q), stack(s)
        deq.append(dequantize(q, s))
    got = moe_ops.expert_ffn_a2a(x, w, idx, None, None, None, mesh,
                                 quant=quant, interpret=True,
                                 collective_dtype="int8")
    want = moe_ops.expert_ffn_a2a(x, w, idx, *deq, mesh,
                                  collective_dtype="bf16")
    scale = float(jnp.max(jnp.abs(np.asarray(want, np.float32)))) + 1e-9
    np.testing.assert_allclose(np.asarray(got, np.float32) / scale,
                               np.asarray(want, np.float32) / scale,
                               atol=2e-2)


# ---------------------------------------------------------------------------
# Scale-plane exchange correctness (dense fallback; the ragged TPU branch
# consumes the SAME offset/size arrays by construction — XLA:CPU has no
# ragged_all_to_all to execute, so the dense path carries the contract)
# ---------------------------------------------------------------------------

def _exact_rows(rng, T, H):
    """Rows whose int8 round trip is EXACT: amax = 127/64 makes the
    per-row scale exactly 1/64 (an IEEE-exact division), and every entry
    m/64 with |m| <= 127 survives quantize->dequantize bit-for-bit (and
    is bf16-representable).  Any scale-plane misalignment — a scale
    landing on the wrong row under skew, chunking, or region offsets —
    then shows up as a hard numeric difference, not as noise."""
    m = rng.integers(-127, 128, (T, H)).astype(np.float32)
    m[:, 0] = 127.0                     # pin amax -> scale = 1/64 exactly
    return jnp.asarray(m / 64.0, jnp.bfloat16)


def test_scale_plane_alignment_byte_exact_under_skew(mesh):
    """Dispatch-only quantization on exactly-representable rows must equal
    the bf16 wire BIT-FOR-BIT, under worst-case routing skew (every token
    to one shard's experts) and multi-chunk dispatch — the scale plane
    provably rides the same offsets as its payload rows."""
    rng = np.random.default_rng(5)
    T, E, H, k = 32, 16, 64, 2
    x = _exact_rows(rng, T, H)
    wg = jnp.asarray(rng.standard_normal((E, H, 16)) * 0.2, jnp.bfloat16)
    wu = jnp.asarray(rng.standard_normal((E, H, 16)) * 0.2, jnp.bfloat16)
    wd = jnp.asarray(rng.standard_normal((E, 16, H)) * 0.2, jnp.bfloat16)
    cases = {
        "skewed": jnp.tile(jnp.asarray([[0, 1]], jnp.int32), (T, 1)),
        "random": jnp.asarray(rng.integers(0, E, (T, k)), jnp.int32),
    }
    for name, idx in cases.items():
        w = jnp.abs(jnp.asarray(rng.standard_normal((T, k)),
                                jnp.float32)) * 0.5
        for chunk in (None, 2):
            a = moe_ops.expert_ffn_a2a(x, w, idx, wg, wu, wd, mesh,
                                       chunk_tokens=chunk,
                                       collective_dtype="bf16")
            b = moe_ops.expert_ffn_a2a(x, w, idx, wg, wu, wd, mesh,
                                       chunk_tokens=chunk,
                                       collective_dtype="int8-dispatch")
            np.testing.assert_array_equal(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                err_msg=f"case={name} chunk={chunk}")


def test_quantize_rows_round_trip_shapes():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((6, 32)), jnp.float32)
    q, s = quantize_rows(x)
    assert q.shape == (6, 32) and q.dtype == jnp.int8
    assert s.shape == (6,) and s.dtype == jnp.float32
    back = dequantize_rows(q, s)
    assert float(jnp.max(jnp.abs(back - x))) <= float(
        jnp.max(jnp.abs(x))) / 127.0 + 1e-6


# ---------------------------------------------------------------------------
# Quantized allreduce (psum fallback / TP)
# ---------------------------------------------------------------------------

def test_quantized_psum_matches_psum_on_ep_axes(mesh):
    """expert_ffn dispatch='psum' under the int8 wire == the exact psum
    oracle within the combine bound — the EQuARX allreduce swap is
    numerically invisible at the documented tolerance."""
    x, w, idx, wg, wu, wd = _case(13, 16, 16)
    exact = moe_ops.expert_ffn(x, w, idx, wg, wu, wd, mesh=mesh,
                               dispatch="psum", collective_dtype="bf16")
    quant = moe_ops.expert_ffn(x, w, idx, wg, wu, wd, mesh=mesh,
                               dispatch="psum", collective_dtype="int8")
    assert _rel_rms(quant, exact, exact) <= 2e-2
    np.testing.assert_allclose(np.asarray(quant, np.float32),
                               np.asarray(exact, np.float32),
                               atol=6e-2, rtol=6e-2)


def test_quantized_psum_single_tp_axis(mesh):
    """The helper reduces over ONE named axis too (the dense-TP
    allreduce shape): parity vs lax.psum over 'tp', including a row
    count that does not divide the shard count (internal padding)."""
    rng = np.random.default_rng(17)
    for T in (8, 9):
        xs = jnp.asarray(rng.standard_normal((2 * T, 16)), jnp.float32)

        def body(xl):
            return (quantized_psum(xl, "tp", 2),
                    jax.lax.psum(xl, "tp"))

        from jax.sharding import PartitionSpec as P
        got, want = shard_map(
            body, mesh=mesh, in_specs=(P("tp"),), out_specs=(P(), P()),
            check_vma=False)(xs)
        assert _rel_rms(got, want, want) <= 2e-2
        assert got.shape == want.shape == (T, 16)


# ---------------------------------------------------------------------------
# Accuracy harness on real routed traces — the gate behind `auto`
# ---------------------------------------------------------------------------

def _traffic_engine():
    from llm_d_tpu.engine.engine import EngineConfig, EngineCore
    from llm_d_tpu.engine.request import Request
    from llm_d_tpu.ops.sampling import SamplingParams
    e = EngineCore(EngineConfig(
        model="tiny-moe", block_size=4, num_blocks=64, max_num_seqs=4,
        max_num_batched_tokens=64, min_token_bucket=16, min_seq_bucket=4))
    reqs = [Request(
        request_id=f"r{i}",
        prompt_token_ids=[(7 * i + 13 * j) % 500 + 1 for j in range(12)],
        sampling=SamplingParams(temperature=0.0, max_tokens=6,
                                ignore_eos=True)) for i in range(3)]
    out = e.generate(reqs)
    streams = [r.prompt_token_ids + out[r.request_id] for r in reqs]
    return e, streams


def test_collective_harness_bounds_on_real_trace():
    """Real routed traces (a tiny-moe engine's served sequences replayed
    through the model with trace capture) must hold the documented
    per-collective bounds — the measured gate that justifies `auto`
    resolving to the int8 wire on TPU."""
    e, streams = _traffic_engine()
    trace = acc.harvest_routed_trace(e, streams)
    assert trace["x"].shape[0] == 1          # tiny-moe: one MoE layer
    assert trace["x"].shape[1] >= 32         # traffic actually traced
    reports = acc.layer_reports(trace, e.params["moe_layers"])
    for rep in reports:
        assert rep["dispatch"]["rel_rms"] <= rep["dispatch"]["bound_rel_rms"], rep
        assert rep["combine"]["rel_rms"] <= rep["combine"]["bound_rel_rms"], rep
        assert rep["within_bounds"] is True
        assert rep["end_to_end"]["rel_rms"] <= (
            acc.DISPATCH_REL_BOUND + acc.COMBINE_REL_BOUND)


def test_auto_gating_follows_backend():
    """`auto` = int8 exactly where the harness gate applies (TPU, where
    the ICI is the scarce resource) and the exact bf16 wire elsewhere —
    the MLA-absorption-harness gating pattern."""
    assert resolve_collective_dtype("auto", backend="tpu") == "int8"
    assert resolve_collective_dtype("auto", backend="cpu") == "bf16"
    assert resolve_collective_dtype(None, backend="cpu") == "bf16"
    assert resolve_collective_dtype("int8", backend="cpu") == "int8"
    assert resolve_collective_dtype("bf16", backend="tpu") == "bf16"


# ---------------------------------------------------------------------------
# Env knob + byte accounting + engine metric
# ---------------------------------------------------------------------------

def test_env_knob_rejects_unsupported_dtype(monkeypatch):
    monkeypatch.setenv("LLMD_COLLECTIVE_DTYPE", "fp4")
    assert resolve_collective_dtype(backend="cpu") == "bf16"   # auto
    assert resolve_collective_dtype(backend="tpu") == "int8"   # auto
    monkeypatch.setenv("LLMD_COLLECTIVE_DTYPE", "int8")
    assert resolve_collective_dtype(backend="cpu") == "int8"
    monkeypatch.setenv("LLMD_COLLECTIVE_DTYPE", "bf16")
    assert resolve_collective_dtype(backend="tpu") == "bf16"
    with pytest.raises(ValueError):
        resolve_collective_dtype("int4")
    with pytest.raises(ValueError):
        a2a_row_bytes(64, "fp4")


def test_wire_byte_accounting_acceptance_ratio():
    """The acceptance arithmetic itself: int8 dispatch+combine bytes are
    <= 0.35x the f32-combine baseline at the paper model's hidden size,
    and the per-mode table is internally consistent."""
    H, k = 7168, 8
    base = ep_a2a_bytes_per_token(H, k, "f32-combine")
    int8 = ep_a2a_bytes_per_token(H, k, "int8")
    assert int8 / base <= 0.35, (int8, base)
    row = a2a_row_bytes(H, "int8")
    assert row["dispatch"] == H + 4 + 4      # payload + scale + index
    assert row["combine"] == H + 4
    assert ep_a2a_bytes_per_token(H, k, "bf16", layers=3) == \
        3 * k * (a2a_row_bytes(H, "bf16")["dispatch"] + 2 * H)


def test_engine_exports_collective_bytes(devices):
    """A multi-device MoE engine charges the exchange bytes per computed
    token to llmd_tpu:collective_bytes_total, labeled by collective and
    resolved wire dtype; a single-device engine ships none."""
    from llm_d_tpu.engine.engine import EngineConfig, EngineCore
    from llm_d_tpu.engine.request import Request
    from llm_d_tpu.ops.sampling import SamplingParams
    from llm_d_tpu.utils.metrics import parse_prometheus_text
    kw = dict(model="tiny-moe", block_size=4, num_blocks=64,
              max_num_seqs=4, max_num_batched_tokens=64,
              min_token_bucket=16, min_seq_bucket=4)
    e = EngineCore(EngineConfig(**kw, mesh=MeshConfig(tp=2),
                                allow_device_subset=True),
                   devices=devices[:2])
    assert e._collective_wire == "bf16"      # auto on CPU
    e.generate([Request(
        request_id="m", prompt_token_ids=list(range(1, 9)),
        sampling=SamplingParams(temperature=0.0, max_tokens=4,
                                ignore_eos=True))])
    parsed = parse_prometheus_text(e.metrics.render().decode())
    got = {k: v for k, v in parsed.items()
           if "collective_bytes" in k and "{" in k}
    assert any("dispatch" in k for k in got), parsed.keys()
    assert any("combine" in k for k in got), parsed.keys()
    # Consistency with the byte model: dispatch bytes = N computed
    # tokens x k x dispatch-row bytes (Lm = 1 on tiny-moe), and the
    # combine counter charges the same N tokens at combine-row bytes.
    row = a2a_row_bytes(e.model_config.hidden_size, "bf16")
    k_tok = e.model_config.num_experts_per_tok
    dispatch_val = [v for k, v in got.items() if "dispatch" in k][0]
    combine_val = [v for k, v in got.items() if "combine" in k][0]
    n_tok = dispatch_val / (k_tok * row["dispatch"])
    assert n_tok == int(n_tok) and n_tok >= 8, (dispatch_val, row)
    assert combine_val == n_tok * k_tok * row["combine"]

    single = EngineCore(EngineConfig(**kw), devices=[devices[0]])
    assert single._collective_wire is None


def test_psum_bytes_model():
    """The allreduce accounting model (charged when a non-power-of-two
    ep forces the psum fallback on every step — a mesh E % ep != 0
    cannot even build, the expert weights shard over the EP axes):
    k-independent, full-activation, both ring legs; int8 mode charges
    the quantized reduce-scatter + all-gather wire."""
    from llm_d_tpu.parallel.quant_collectives import psum_bytes_per_token
    H = 7168
    assert psum_bytes_per_token(H, "bf16") == 2 * 4 * H     # f32 psum
    assert psum_bytes_per_token(H, "int8") == 2 * (H + 4)
    # ~4x fewer wire bytes than the f32 psum (the quantized_psum claim).
    assert psum_bytes_per_token(H, "int8") \
        <= 0.26 * psum_bytes_per_token(H, "bf16")
    with pytest.raises(ValueError):
        psum_bytes_per_token(H, "fp4")
