"""Transfer-cost-aware KV placement (PR 20).

Unit tier for the pieces behind the kv-placement-scorer: the byte/tier-
aware PrefixIndex extensions (``restorable_prefix``, ``attach_inproc``,
the query-refreshes-LRU fix), the link-cost TransferCostModel, the new
``llmd_tpu:kv_events_total`` / ``llmd_tpu:kv_placement_decision_total``
counters, and the scorer's saturation property — cached-prefix benefit
is bounded by avoided prefill cost while queue cost grows without
bound, so a hot pinned replica LOSES to an idle peer-restore candidate
(the un-pinning the docs/cluster-sim.md case study asks for).
"""

import pytest

from llm_d_tpu.epp.datastore import Datastore, EndpointState
from llm_d_tpu.epp.indexer import (
    DEVICE_TIER,
    HOST_TIER,
    PrefixIndex,
    RestorePlan,
)
from llm_d_tpu.epp.plugins import KvPlacementScorer, RequestCtx
from llm_d_tpu.predictor.model import TransferCostModel
from llm_d_tpu.utils.hashing import hash_token_blocks
from llm_d_tpu.utils.metrics import EppMetrics


K = [bytes([i]) * 8 for i in range(16)]      # opaque block hashes


# ---------------------------------------------------------------------------
# PrefixIndex: bytes/tier tracking + restorable_prefix
# ---------------------------------------------------------------------------


def test_restorable_prefix_local_then_peer():
    idx = PrefixIndex()
    # Candidate A holds blocks 0-1; peer B holds 0-3 (so B can restore
    # the contiguous continuation 2-3 to A).
    idx.on_event("A", "BlockStored", K[0:2], nbytes=1024)
    idx.on_event("B", "BlockStored", K[0:4], nbytes=2048)
    plan = idx.restorable_prefix(K[0:4], "A")
    assert plan.local_blocks == 2
    assert plan.peer_blocks == 2
    assert plan.source == "B"
    assert plan.tier == DEVICE_TIER
    assert plan.nbytes == 2 * 2048
    assert plan.total_blocks == 4


def test_restorable_prefix_prefers_longest_then_device_tier():
    idx = PrefixIndex()
    # host tier covers 3 continuation blocks, device peer only 2:
    # longest contiguous run wins even at host tier...
    idx.on_event("host-pool", "BlockStored", K[0:3],
                 nbytes=4096, tier=HOST_TIER)
    idx.on_event("B", "BlockStored", K[0:2], nbytes=4096)
    plan = idx.restorable_prefix(K[0:3], "A")
    assert (plan.source, plan.peer_blocks) == ("host-pool", 3)
    assert plan.tier == HOST_TIER
    # ...but on equal length the device-tier source is preferred.
    idx.on_event("B", "BlockStored", [K[2]], nbytes=4096)
    plan = idx.restorable_prefix(K[0:3], "A")
    assert (plan.source, plan.tier) == ("B", DEVICE_TIER)


def test_restorable_prefix_stops_at_gap_and_excludes_self():
    idx = PrefixIndex()
    idx.on_event("A", "BlockStored", [K[0]])
    idx.on_event("B", "BlockStored", [K[1]])      # K[2] nowhere -> gap
    idx.on_event("B", "BlockStored", [K[3]])
    plan = idx.restorable_prefix(K[0:4], "A")
    assert (plan.local_blocks, plan.peer_blocks) == (1, 1)
    # A block only the candidate itself holds is NOT peer-restorable.
    solo = PrefixIndex()
    solo.on_event("A", "BlockStored", K[0:2])
    plan = solo.restorable_prefix(K[0:2], "A")
    assert plan.local_blocks == 2 and plan.peer_blocks == 0
    assert plan.source is None
    empty = solo.restorable_prefix(K[4:6], "A")
    assert empty.total_blocks == 0 and empty.nbytes == 0


def test_attach_inproc_routes_events_with_bytes_and_removal():
    idx = PrefixIndex()
    sink = idx.attach_inproc("sim-a:8200", block_nbytes=8192)
    sink("BlockStored", K[0:2])
    plan = idx.restorable_prefix(K[0:2], "other")
    assert plan.peer_blocks == 2 and plan.nbytes == 2 * 8192
    sink("BlockRemoved", [K[1]])
    assert idx.restorable_prefix(K[0:2], "other").peer_blocks == 1
    idx.remove_endpoint("sim-a:8200")
    assert idx.size == 0


def test_query_hit_refreshes_lru_recency():
    # The longest_prefix LRU bugfix: a block queried on every schedule
    # but never re-stored must NOT be the first eviction victim.
    idx = PrefixIndex(capacity=4)
    idx.on_event("A", "BlockStored", K[0:4])
    for fresh in K[4:10]:
        assert idx.longest_prefix([K[0]], "A") == 1   # touch the hot block
        idx.on_event("A", "BlockStored", [fresh])     # churn past capacity
    assert idx.longest_prefix([K[0]], "A") == 1, \
        "repeatedly-queried block evicted by capacity churn"
    # Control: an un-queried sibling from the same store DID age out.
    assert idx.longest_prefix([K[1]], "A") == 0


def test_kv_event_metrics_count_by_type():
    m = EppMetrics()
    idx = PrefixIndex(metrics=m)
    idx.on_event("A", "BlockStored", K[0:3])
    idx.on_event("A", "BlockRemoved", [K[0]])
    idx.remove_endpoint("A")

    def count(event_type):
        return m.registry.get_sample_value(
            "llmd_tpu:kv_events_total", {"type": event_type})

    assert count("BlockStored") == 3
    assert count("BlockRemoved") == 1
    assert count("AllBlocksCleared") == 1


# ---------------------------------------------------------------------------
# TransferCostModel
# ---------------------------------------------------------------------------


def test_transfer_cost_analytic_scales_with_bytes_and_link():
    tm = TransferCostModel(peer_gbps=16.0, host_gbps=64.0, setup_ms=2.0)
    assert tm.restore_ms(0) == 0.0
    one_gb = 10 ** 9
    # 1 GB at 16 Gb/s = 500 ms + setup; the host link is 4x faster.
    assert tm.restore_ms(one_gb, "peer") == pytest.approx(502.0, rel=0.01)
    assert tm.restore_ms(one_gb, "host") == pytest.approx(127.0, rel=0.01)
    assert tm.restore_ms(2 * one_gb, "peer") > tm.restore_ms(one_gb, "peer")


def test_transfer_cost_fit_overrides_analytic_prior():
    tm = TransferCostModel(peer_gbps=16.0, setup_ms=2.0, min_samples=8)
    # The observed link is 10x slower than the configured prior.
    for i in range(1, 12):
        nbytes = i * 10 ** 7
        tm.observe("peer", nbytes, (2.0 + nbytes * 8e-6 * 10 / 16.0) / 1e3)
    assert tm.trained("peer")
    fitted = tm.restore_ms(10 ** 8, "peer")
    analytic = TransferCostModel(
        peer_gbps=16.0, setup_ms=2.0).restore_ms(10 ** 8, "peer")
    assert fitted == pytest.approx(10 * (analytic - 2.0) + 2.0, rel=0.05)


def test_transfer_cost_roundtrips_through_dict():
    tm = TransferCostModel(peer_gbps=8.0, host_gbps=32.0, setup_ms=1.0)
    for i in range(1, 10):
        tm.observe("host", i * 10 ** 6, 0.001 * i)
    clone = TransferCostModel.from_dict(tm.to_dict())
    assert clone.restore_ms(5 * 10 ** 6, "host") == \
        pytest.approx(tm.restore_ms(5 * 10 ** 6, "host"))
    assert clone.restore_ms(5 * 10 ** 6, "peer") == \
        pytest.approx(tm.restore_ms(5 * 10 ** 6, "peer"))


def test_transfer_cost_env_knobs(monkeypatch):
    monkeypatch.setenv("LLMD_KV_TRANSFER_PEER_GBPS", "1.0")
    monkeypatch.setenv("LLMD_KV_TRANSFER_SETUP_MS", "0.0")
    tm = TransferCostModel()
    # 10^9 bytes * 8 bits / 1 Gb/s = 8000 ms, no setup.
    assert tm.restore_ms(10 ** 9, "peer") == pytest.approx(8000.0, rel=0.01)


# ---------------------------------------------------------------------------
# KvPlacementScorer: cost model + saturation + verdicts
# ---------------------------------------------------------------------------


BLOCK = 64


def _scorer(indexer, metrics=None, **params):
    eps = [EndpointState(address="10.0.0.1:8200", ready=True),
           EndpointState(address="10.0.0.2:8200", ready=True)]
    ds = Datastore(eps)
    p = dict({"blockSize": BLOCK, "kvBytesPerToken": 131072}, **params)
    return (KvPlacementScorer("kv-placement-scorer", p, ds,
                              indexer=indexer, metrics=metrics), eps)


def _ctx(n_tokens=4 * BLOCK):
    return RequestCtx(body={}, prompt_text="x" * (4 * n_tokens),
                      token_ids=list(range(n_tokens)))


def test_scorer_saturates_hot_pinned_replica_loses_to_idle_peer():
    # The pinning pathology, un-pinned by construction: the replica
    # holding the whole prefix is deeply queued; an idle peer can
    # restore the same prefix for a bounded transfer cost.  Expected
    # TTFT must rank the idle peer first no matter how large the queue
    # grows — cached benefit saturates, queue cost does not.
    idx = PrefixIndex()
    ctx = _ctx()
    keys = hash_token_blocks(ctx.token_ids, BLOCK)
    scorer, eps = _scorer(idx)
    idx.on_event(eps[0].address, "BlockStored", keys, nbytes=BLOCK * 131072)
    eps[0].num_waiting = 40            # pinned AND drowning
    eps[1].num_waiting = 0             # idle, cold
    scores = scorer.score(ctx, eps)
    assert scores[eps[1].address] > scores[eps[0].address]
    plans = ctx._kv_plan_map
    assert plans[eps[0].address]["verdict"] == "local_hit"
    assert plans[eps[1].address]["verdict"] == "peer_restore"
    assert plans[eps[1].address]["source"] == eps[0].address
    assert plans[eps[1].address]["restore_bytes"] == \
        len(keys) * BLOCK * 131072


def test_scorer_prefers_cached_replica_at_equal_load():
    idx = PrefixIndex()
    ctx = _ctx()
    keys = hash_token_blocks(ctx.token_ids, BLOCK)
    scorer, eps = _scorer(idx)
    idx.on_event(eps[0].address, "BlockStored", keys, nbytes=BLOCK * 131072)
    scores = scorer.score(ctx, eps)     # both idle
    assert scores[eps[0].address] > scores[eps[1].address]


def test_scorer_on_picked_stamps_header_plan_and_metric():
    from llm_d_tpu.utils.lifecycle import KV_PLACEMENT_HEADER

    m = EppMetrics()
    idx = PrefixIndex(metrics=m)
    ctx = _ctx()
    keys = hash_token_blocks(ctx.token_ids, BLOCK)
    scorer, eps = _scorer(idx, metrics=m)
    idx.on_event(eps[0].address, "BlockStored", keys, nbytes=BLOCK * 131072)
    scorer.score(ctx, eps)
    scorer.on_picked(ctx, eps[1], "default")
    assert ctx.headers[KV_PLACEMENT_HEADER] == "peer_restore"
    assert ctx.kv_restore_plan["peer_blocks"] == len(keys)
    assert ctx.kv_restore_plan["restore_ms"] > 0
    assert m.registry.get_sample_value(
        "llmd_tpu:kv_placement_decision_total",
        {"verdict": "peer_restore"}) == 1


def test_scorer_recompute_without_index_coverage():
    idx = PrefixIndex()
    ctx = _ctx()
    scorer, eps = _scorer(idx)
    scores = scorer.score(ctx, eps)
    assert set(scores) == {e.address for e in eps}
    assert all(v == 1.0 for v in scores.values())   # equal cost -> minmax 1.0
    assert all(p["verdict"] == "recompute"
               for p in ctx._kv_plan_map.values())


def test_scheduler_wires_kv_placement_scorer():
    from llm_d_tpu.epp.config import parse_config
    from llm_d_tpu.epp.scheduler import EppScheduler

    yaml = """
kind: EndpointPickerConfig
plugins:
- type: single-profile-handler
- type: kv-placement-scorer
  parameters: {blockSize: 64}
- type: max-score-picker
schedulingProfiles:
- name: default
  plugins:
  - pluginRef: kv-placement-scorer
  - pluginRef: max-score-picker
"""
    idx = PrefixIndex()
    eps = [EndpointState(address="10.0.0.1:8200", ready=True)]
    sched = EppScheduler(parse_config(yaml), Datastore(eps), indexer=idx)
    scorer = sched.plugins["kv-placement-scorer"]
    assert isinstance(scorer, KvPlacementScorer)
    assert scorer.indexer is idx
    result = sched.schedule(_ctx())
    assert result.primary is not None


def test_restore_plan_dataclass_defaults():
    plan = RestorePlan()
    assert plan.total_blocks == 0
    assert plan.tier == DEVICE_TIER
