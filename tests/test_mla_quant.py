"""Int8 MLA latent cache contract (kv_cache_dtype=int8 + MLA), end to end.

The claim under test is the ISSUE-6 acceptance set: the quantized MLA
decode/prefill kernels match the bf16 latent within an explicit bound
(kernel AND XLA fallback are the same dequantize-then-attend numerics),
the per-absorption accuracy harness holds its documented bounds on REAL
decode traces (the latent feeds TWO weight absorptions — score via W_uk,
value via W_uv — so each is bounded separately), the latent block pool is
>= 1.9x bf16 at a fixed HBM budget, the offload tier round-trips the
latent + scale plane byte-exactly, and the P->D wire REJECTS a latent
dtype mismatch instead of reinterpreting it.  Everything runs on CPU:
Pallas via ``interpret=True``, engine paths via the XLA fallback.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from llm_d_tpu.engine.engine import EngineConfig, EngineCore, derive_num_blocks
from llm_d_tpu.engine.request import Request
from llm_d_tpu.models.config import get_config
from llm_d_tpu.ops import attention as A
from llm_d_tpu.ops import mla_accuracy as acc
from llm_d_tpu.ops.pallas.mla_attention import mla_paged_decode_update
from llm_d_tpu.ops.pallas.mla_prefill import mla_flash_prefill
from llm_d_tpu.ops.quant import dequantize_kv_block, quantize_kv_block
from llm_d_tpu.ops.sampling import SamplingParams
from llm_d_tpu.transfer.connector import _pack_blocks, _scatter_blocks

# Same quantization-error contract as the dense int8 cache: one symmetric
# scale per 576-wide latent row, per-element error <= amax/254; the
# softmax-weighted row sums land well inside this band.
ATOL_VS_BF16 = 8e-2


def greedy_req(rid, prompt, n=4, **kw):
    return Request(request_id=rid, prompt_token_ids=list(prompt),
                   sampling=SamplingParams(temperature=0.0, max_tokens=n,
                                           ignore_eos=True), **kw)


ENGINE_KW = dict(model="tiny-mla", block_size=4, num_blocks=64,
                 max_num_seqs=4, max_num_batched_tokens=64,
                 min_token_bucket=16, min_seq_bucket=4)

PROMPT = [7, 3, 9, 1, 4, 6, 2, 8, 5, 0, 11, 13]


# ---------------------------------------------------------------------------
# Pallas decode kernel parity (interpret mode)
# ---------------------------------------------------------------------------

def _decode_case(seed, S, H, F, bs, num_blocks, seq_lens, L=3):
    rng = np.random.default_rng(seed)
    kv = jnp.asarray(rng.standard_normal((L, num_blocks * bs, F)),
                     jnp.bfloat16)
    B = max(-(-int(max(seq_lens)) // bs), 1)
    perm = rng.permutation(num_blocks - 1)[: S * B] + 1
    bt = jnp.asarray(perm.reshape(S, B), jnp.int32)
    q = jnp.asarray(rng.standard_normal((S, H, F)), jnp.bfloat16)
    row = jnp.asarray(rng.standard_normal((S, F)), jnp.bfloat16)
    return q, row, kv, bt, jnp.asarray(seq_lens, jnp.int32)


def _bf16_decode_oracle(q, row, kv, bt, lens, bs, scale, layer):
    S, H, F = q.shape
    slot = (jnp.take_along_axis(bt, ((lens - 1) // bs)[:, None],
                                axis=1)[:, 0] * bs + (lens - 1) % bs)
    kv, _ = A.write_kv(kv, kv, row.reshape(S, 1, F), row.reshape(S, 1, F),
                       slot, layer=layer)
    out = A.ragged_paged_attention_reference(
        q, kv, kv, jnp.arange(S, dtype=jnp.int32), lens - 1, bt, lens,
        block_size=bs, scale=scale, layer=layer)
    return out, slot


def test_mla_decode_kernel_int8_parity():
    """The quantized MLA kernel must (a) EXACTLY match the dequantize-
    then-attend oracle built from the same int8 latent — kernel and XLA
    fallback implement identical numerics — and (b) match the pure-bf16
    latent within the quoted quantization bound; the new row's int8 bytes
    and f32 scale splice back byte-exactly."""
    H, F, bs, L = 4, 128, 32, 3
    seq_lens = [1, bs // 2, bs, bs + 3, 3 * bs]
    S = len(seq_lens)
    scale = 0.17
    q, row, kv_bf, bt, lens = _decode_case(
        7, S, H, F, bs, num_blocks=S * 3 + 1, seq_lens=seq_lens, L=L)
    layer = jnp.asarray(1, jnp.int32)

    kq, ks = quantize_kv_block(kv_bf, 1)
    rq, rs = quantize_kv_block(row, 1)
    out, kv_u, ks_u = mla_paged_decode_update(
        q, rq, kq, bt, lens, block_size=bs, scale=scale, layer=layer,
        interpret=True, kv_scale=ks, row_scale_new=rs)

    # (a) vs the dequantized-int8 oracle: bf16-rounding-level agreement.
    ref_q, slot = _bf16_decode_oracle(
        q, dequantize_kv_block(rq, rs), dequantize_kv_block(kq, ks),
        bt, lens, bs, scale, layer)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref_q, np.float32),
        atol=2e-2, rtol=2e-2)
    # (b) vs pure bf16: the quantization bound the docs quote.
    ref_bf, _ = _bf16_decode_oracle(q, row, kv_bf, bt, lens, bs, scale,
                                    layer)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref_bf, np.float32),
        atol=ATOL_VS_BF16, rtol=ATOL_VS_BF16)

    # Write-back byte-exact: payload AND scale land where the scatter
    # oracle puts them; untouched layer planes stay untouched.
    np.testing.assert_array_equal(
        np.asarray(kv_u), np.asarray(kq.at[layer, slot].set(rq)))
    np.testing.assert_array_equal(
        np.asarray(ks_u), np.asarray(ks.at[layer, slot].set(rs)))
    np.testing.assert_array_equal(np.asarray(kv_u[0]), np.asarray(kq[0]))
    np.testing.assert_array_equal(np.asarray(ks_u[2]), np.asarray(ks[2]))


@pytest.mark.parametrize("seq_group", [1, 4])
def test_mla_decode_kernel_int8_grouping_and_pad_rows(seq_group):
    """Grouped programs over the int8 latent with ragged lengths and
    zero-length pad rows (clamped dead reads, no write-back) still match
    the oracle."""
    H, F, bs = 4, 128, 32
    real_lens = [1, 7, bs, 2 * bs + 5]
    S = 8
    seq_lens = real_lens + [0] * (S - len(real_lens))
    q, row, kv_bf, bt, lens = _decode_case(
        21 + seq_group, S, H, F, bs, num_blocks=S * 3 + 1,
        seq_lens=seq_lens, L=1)
    bt = bt.at[len(real_lens):].set(0)
    kq, ks = quantize_kv_block(kv_bf, 1)
    rq, rs = quantize_kv_block(row, 1)
    out, _, _ = mla_paged_decode_update(
        q, rq, kq, bt, lens, block_size=bs, scale=0.21,
        layer=jnp.asarray(0, jnp.int32), interpret=True,
        seq_group=seq_group, kv_scale=ks, row_scale_new=rs)
    n = len(real_lens)
    ref, _ = _bf16_decode_oracle(
        q[:n], dequantize_kv_block(rq, rs)[:n],
        dequantize_kv_block(kq, ks), bt[:n], lens[:n], bs, 0.21,
        jnp.asarray(0, jnp.int32))
    np.testing.assert_allclose(
        np.asarray(out[:n], np.float32), np.asarray(ref, np.float32),
        atol=2e-2, rtol=2e-2)


# ---------------------------------------------------------------------------
# Pallas prefill kernel parity (interpret mode)
# ---------------------------------------------------------------------------

def test_mla_prefill_kernel_int8_parity():
    rng = np.random.default_rng(11)
    S, Q, H, F, bs, L = 3, 8, 4, 128, 32, 2
    num_blocks, B = 12, 3
    seq_lens = np.array([5, 40, 96], np.int32)
    kv_bf = jnp.asarray(rng.standard_normal((L, num_blocks * bs, F)),
                        jnp.bfloat16)
    perm = rng.permutation(num_blocks - 1)[: S * B] + 1
    bt = jnp.asarray(perm.reshape(S, B), jnp.int32)
    lens = jnp.asarray(seq_lens)
    layer = jnp.asarray(1, jnp.int32)
    qs = jnp.asarray(rng.standard_normal((S, Q, H, F)), jnp.bfloat16)
    q_pos = jnp.asarray(np.stack(
        [np.clip(np.arange(Q) + l - Q, -1, None) for l in seq_lens]),
        jnp.int32)

    kq, ks = quantize_kv_block(kv_bf, 1)
    out = mla_flash_prefill(
        qs, q_pos, kq, bt, lens, block_size=bs, scale=0.2, layer=layer,
        interpret=True, kv_scale=ks)
    # Same-numerics oracle: the bf16 kernel over the dequantized latent.
    ref_q = mla_flash_prefill(
        qs, q_pos, dequantize_kv_block(kq, ks), bt, lens, block_size=bs,
        scale=0.2, layer=layer, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref_q, np.float32),
        atol=2e-2, rtol=2e-2)
    ref_bf = mla_flash_prefill(
        qs, q_pos, kv_bf, bt, lens, block_size=bs, scale=0.2, layer=layer,
        interpret=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref_bf, np.float32),
        atol=ATOL_VS_BF16, rtol=ATOL_VS_BF16)


# ---------------------------------------------------------------------------
# Per-absorption accuracy harness on REAL decode traces
# ---------------------------------------------------------------------------

def test_absorption_harness_bounds_on_real_trace():
    """Harvest latent rows a bf16 tiny-MLA engine actually wrote, score
    them with the model's own absorbed queries, and assert the documented
    per-absorption bounds — the gate that justified lifting the int8+MLA
    rejection."""
    e = EngineCore(EngineConfig(**ENGINE_KW))
    reqs = [greedy_req(f"t{i}", [(7 * i + 13 * j) % 500 + 1
                                 for j in range(12)], 6) for i in range(4)]
    e.generate(reqs)
    rows = acc.harvest_latent_rows(e)
    assert rows.shape[0] >= 16, rows.shape   # traffic actually traced

    c = get_config("tiny-mla")
    lp = {k: v[0] for k, v in e.params["moe_layers"].items()}
    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (8, c.hidden_size)), jnp.bfloat16)
    q_eff, w_uv = acc.absorbed_queries(
        lp, c, x, jnp.arange(8, dtype=jnp.int32))
    rep = acc.absorption_error_report(
        rows, q_eff, w_uv, c.kv_lora_rank,
        scale=(c.qk_nope_head_dim + c.qk_rope_head_dim) ** -0.5)
    # Both absorptions bounded SEPARATELY (score error enters pre-softmax,
    # value error post-softmax — different amplification paths).
    assert rep["score"]["rel_rms"] <= rep["score"]["bound_rel_rms"], rep
    assert rep["value"]["rel_rms"] <= rep["value"]["bound_rel_rms"], rep
    assert rep["within_bounds"] is True
    assert rep["end_to_end"]["rel_rms"] <= 2 * acc.VALUE_REL_BOUND


# ---------------------------------------------------------------------------
# Block pool + engine e2e
# ---------------------------------------------------------------------------

def test_mla_block_pool_at_least_1p9x_at_same_budget():
    layout = {"kv": 640}                   # deepseek-v3 lane-padded latent
    budget = 4 << 30
    bf16 = derive_num_blocks(budget, layout, 61, 64, "bf16")
    int8 = derive_num_blocks(budget, layout, 61, 64, "int8", 1)
    assert int8 / bf16 >= 1.9, (bf16, int8)


def test_engine_mla_int8_builds_and_generates_deterministically():
    bf = EngineCore(EngineConfig(**ENGINE_KW))
    q8a = EngineCore(EngineConfig(**ENGINE_KW, kv_cache_dtype="int8"),
                     params=bf.params)
    q8b = EngineCore(EngineConfig(**ENGINE_KW, kv_cache_dtype="int8"),
                     params=bf.params)
    assert q8a.kv_cache["kv"].dtype == jnp.int8
    assert q8a.kv_cache["kv_scale"].dtype == jnp.float32
    assert q8a.kv_cache["kv_scale"].shape[-1] == 1   # one scale per row
    a = q8a.generate([greedy_req("a", PROMPT, 6)])["a"]
    b = q8b.generate([greedy_req("b", PROMPT, 6)])["b"]
    assert len(a) == 6 and a == b, (a, b)


def test_mla_latent_dtype_gate(monkeypatch):
    """LLMD_MLA_LATENT_DTYPE gates the latent independently: 'bf16' pins
    it under kv_cache_dtype=int8 (the accuracy escape hatch), 'int8'
    forces it under the bf16 default, invalid values degrade to auto."""
    monkeypatch.setenv("LLMD_MLA_LATENT_DTYPE", "bf16")
    e = EngineCore(EngineConfig(**ENGINE_KW, kv_cache_dtype="int8"))
    assert e.kv_cache_dtype == "bf16" and "kv_scale" not in e.kv_cache
    monkeypatch.setenv("LLMD_MLA_LATENT_DTYPE", "int8")
    e = EngineCore(EngineConfig(**ENGINE_KW))
    assert e.kv_cache_dtype == "int8" and "kv_scale" in e.kv_cache
    monkeypatch.setenv("LLMD_MLA_LATENT_DTYPE", "fp4")
    e = EngineCore(EngineConfig(**ENGINE_KW, kv_cache_dtype="int8"))
    assert e.kv_cache_dtype == "int8"      # invalid env -> auto (follow)


def test_mla_seq_group_env_non_divisor_degrades_to_auto(monkeypatch):
    """Env-knob contract: LLMD_MLA_SEQ_GROUP that does not divide the
    current sequence bucket falls back to auto grouping instead of
    crashing the decode path (S varies with load, the knob must not)."""
    import llm_d_tpu.models.mla as mla_mod
    import llm_d_tpu.ops.pallas.mla_attention as ma

    monkeypatch.setenv("LLMD_MLA_SEQ_GROUP", "7")    # divides no pow2 S
    monkeypatch.setattr(A, "resolve_backend", lambda b: "pallas")
    real = ma.mla_paged_decode_update
    seen = {}

    def spy(*a, **kw):
        seen["seq_group"] = kw.get("seq_group")
        kw["interpret"] = True
        return real(*a, **kw)

    monkeypatch.setattr(ma, "mla_paged_decode_update", spy)
    c = get_config("tiny-mla")
    lp = {k: v[:1] for k, v in EngineCore(
        EngineConfig(**ENGINE_KW)).params["moe_layers"].items()}
    lp = {k: v[0] for k, v in lp.items()}
    S, bs = 2, 16
    F = -(-(c.kv_lora_rank + c.qk_rope_head_dim) // 128) * 128
    kv = jnp.zeros((1, 8 * bs, F), jnp.bfloat16)
    lens = jnp.asarray([3, 5], jnp.int32)
    batch = dict(
        token_ids=jnp.zeros(S, jnp.int32),
        positions=lens - 1,
        token_seq_ids=jnp.arange(S, dtype=jnp.int32),
        token_qpos=jnp.zeros(S, jnp.int32),
        slot_mapping=jnp.asarray([1 * bs + 2, 2 * bs + 4], jnp.int32),
        block_tables=jnp.asarray([[1], [2]], jnp.int32),
        seq_lens=lens,
        qtok_idx=jnp.arange(S, dtype=jnp.int32)[:, None],
    )
    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (S, c.hidden_size)), jnp.bfloat16)
    out, _ = mla_mod.mla_attention_block(
        lp, c, x, batch, kv, bs, "pallas", layer=jnp.int32(0))
    assert out.shape == (S, c.hidden_size)
    assert seen["seq_group"] is None       # non-divisor degraded to auto


# ---------------------------------------------------------------------------
# Offload tier: latent + scale plane round-trip
# ---------------------------------------------------------------------------

def test_offload_mla_int8_byte_exact_and_restore_parity():
    engine = EngineCore(EngineConfig(
        model="tiny-mla", block_size=4, num_blocks=16, max_num_seqs=4,
        max_num_batched_tokens=64, min_token_bucket=16, min_seq_bucket=4,
        kv_offload_blocks=64, kv_cache_dtype="int8"))
    first = engine.generate([greedy_req("a1", PROMPT, 4)])["a1"]
    assert engine.host_tier.saves >= 3
    from llm_d_tpu.engine.offload import (
        _pack_block_slab, _slab_layout, _unpack_block_slab)
    blob = next(iter(engine.host_tier._store.values()))
    L = engine.model_config.num_layers
    slab = _unpack_block_slab(blob, _slab_layout(engine), L, 4)
    assert slab["kv"].dtype == np.int8
    assert slab["kv_scale"].dtype == np.float32
    assert _pack_block_slab(slab) == blob      # byte-exact round trip

    for i in range(6):
        filler = [(100 + 17 * i + j) % 500 for j in range(12)]
        engine.generate([greedy_req(f"f{i}", filler, 2)])
    assert engine.kv_manager.eviction_count > 0
    r2 = greedy_req("a2", PROMPT, 4)
    second = engine.generate([r2])["a2"]
    assert second == first
    assert engine.host_tier.loads > 0
    assert r2.num_cached_prompt_tokens >= 8


# ---------------------------------------------------------------------------
# P->D wire: latent dtype rejection + int8-to-int8 parity
# ---------------------------------------------------------------------------

def test_transfer_wire_mla_latent_dtype_rejection():
    bf = EngineCore(EngineConfig(**ENGINE_KW))
    q8 = EngineCore(EngineConfig(**ENGINE_KW, kv_cache_dtype="int8"),
                    params=bf.params)
    q8b = EngineCore(EngineConfig(**ENGINE_KW, kv_cache_dtype="int8"),
                     params=bf.params)
    q8.generate([greedy_req("a", PROMPT[:8], 2)])
    bf.generate([greedy_req("a", PROMPT[:8], 2)])
    blocks = [1, 2]
    blob8 = _pack_blocks(q8, blocks)
    blob16 = _pack_blocks(bf, blocks)
    # ~Half the bytes (+ scale plane and headers; the tiny model's narrow
    # 128-wide padded row keeps overhead visible).
    assert len(blob8) < 0.65 * len(blob16), (len(blob8), len(blob16))

    # int8 -> int8: latent payload AND scales land byte-exactly.
    _scatter_blocks(q8b, blocks, blob8)
    slots = slice(blocks[0] * 4, (blocks[-1] + 1) * 4)
    for name in q8.kv_cache:
        np.testing.assert_array_equal(
            np.asarray(q8.kv_cache[name][:, slots]),
            np.asarray(q8b.kv_cache[name][:, slots]), err_msg=name)

    # int8-latent producer -> bf16-latent consumer: REJECTED (the buffer
    # set differs — kv vs kv+kv_scale), never reinterpreted; and the
    # reverse direction too.
    with pytest.raises(ValueError):
        _scatter_blocks(bf, blocks, blob8)
    with pytest.raises(ValueError):
        _scatter_blocks(q8b, blocks, blob16)


def test_pd_e2e_mla_int8_parity():
    """Producer -> consumer over the real connector with int8 latent
    caches on both sides: the pulled prefix decodes exactly like a local
    int8 run."""
    from llm_d_tpu.transfer.connector import KVConnectorConfig, TpuConnector
    from llm_d_tpu.engine.request import RequestState
    import time
    kw = dict(ENGINE_KW, kv_cache_dtype="int8")
    baseline = EngineCore(EngineConfig(**kw))
    producer = EngineCore(EngineConfig(**kw), params=baseline.params)
    producer.kv_connector = TpuConnector(
        KVConnectorConfig(kv_role="kv_producer", host="127.0.0.1"))
    consumer = EngineCore(EngineConfig(**kw), params=baseline.params)
    consumer.kv_connector = TpuConnector(
        KVConnectorConfig(kv_role="kv_consumer", timeout_ms=5000))
    try:
        prompt = [3, 1, 4, 1, 5, 9, 2, 6]
        want = baseline.generate([greedy_req("b", prompt, 4)])["b"]
        preq = greedy_req("pd", prompt, 1, do_remote_decode=True)
        producer.add_request(preq)
        for _ in range(500):
            producer.step()
            if preq.state == RequestState.FINISHED_REMOTE_PREFILL:
                break
            time.sleep(0.001)
        assert preq.state == RequestState.FINISHED_REMOTE_PREFILL
        dreq = greedy_req("pd", prompt, 4, do_remote_prefill=True,
                          kv_transfer_params=preq.kv_transfer_params)
        got = consumer.generate([dreq])["pd"]
        assert got == want, (got, want)
    finally:
        producer.kv_connector.close()
        consumer.kv_connector.close()
