"""Async scheduling (pipelined fused decode) parity vs the sync path.

The reference's --async-scheduling "reduces white space between engine
steps" (decode.yaml:77,97); here the engine keeps one fused decode block in
flight and dispatches its successor speculatively before retiring it.  The
contract under test: pipelining must never change tokens — stops discovered
at retire discard the successor's tokens for that request, new arrivals
drain the pipeline, aborts in flight are honored.
"""

import numpy as np
import pytest

from llm_d_tpu.engine.engine import EngineConfig, EngineCore
from llm_d_tpu.engine.request import Request
from llm_d_tpu.ops.sampling import SamplingParams


def _cfg(async_sched, **kw):
    base = dict(model="tiny", block_size=4, num_blocks=64, max_num_seqs=8,
                max_num_batched_tokens=64, min_token_bucket=16,
                min_seq_bucket=4, num_scheduler_steps=4,
                async_scheduling=async_sched)
    base.update(kw)
    return EngineConfig(**base)


def _reqs(tag="r"):
    """Varied prompts, max_tokens ending mid-block and on block boundaries,
    greedy and seeded-sampled requests."""
    cases = [
        ([1, 2, 3, 4, 5], 16, 0.0, None),      # 4 full blocks
        ([7, 8, 9], 10, 0.0, None),            # stops mid-block 3
        ([11, 12, 13, 14], 6, 0.0, None),      # stops mid-block 2
        ([3, 1, 4, 1, 5, 9], 13, 0.7, 1234),   # seeded sampling
        ([2, 7, 1, 8], 3, 0.0, None),          # shorter than one block
    ]
    return [
        Request(request_id=f"{tag}{i}", prompt_token_ids=p,
                sampling=SamplingParams(temperature=t, max_tokens=m,
                                        seed=s, ignore_eos=True))
        for i, (p, m, t, s) in enumerate(cases)
    ]


def test_async_matches_sync():
    sync = EngineCore(_cfg(False)).generate(_reqs())
    async_ = EngineCore(_cfg(True)).generate(_reqs())
    assert sync == async_
    assert all(len(v) for v in sync.values())


def test_async_pipeline_actually_engages():
    eng = EngineCore(_cfg(True))
    reqs = _reqs()
    for r in reqs:
        eng.add_request(r)
    engaged = False
    for _ in range(200):
        if not eng.has_work():
            break
        eng.step()
        engaged = engaged or eng._inflight is not None
    assert engaged, "pipeline never went in flight"
    assert eng._inflight is None


@pytest.mark.slow
def test_async_late_arrival_drains_and_matches_solo():
    eng = EngineCore(_cfg(True))
    first = _reqs("a")
    for r in first:
        eng.add_request(r)
    # Step until the decode pipeline is in flight, then add a newcomer.
    for _ in range(100):
        eng.step()
        if eng._inflight is not None:
            break
    assert eng._inflight is not None
    late = Request(request_id="late", prompt_token_ids=[9, 9, 8, 7],
                   sampling=SamplingParams(temperature=0.0, max_tokens=9,
                                           ignore_eos=True))
    eng.add_request(late)
    for _ in range(500):
        if not eng.has_work():
            break
        eng.step()
    assert not eng.has_work()
    assert len(late.output_token_ids) == 9
    # The newcomer's tokens match a solo sync run (batching-invariance).
    solo = EngineCore(_cfg(False)).generate(
        [Request(request_id="late", prompt_token_ids=[9, 9, 8, 7],
                 sampling=SamplingParams(temperature=0.0, max_tokens=9,
                                         ignore_eos=True))])
    assert list(late.output_token_ids) == solo["late"]


def test_async_abort_in_flight():
    eng = EngineCore(_cfg(True))
    reqs = _reqs("a")
    for r in reqs:
        eng.add_request(r)
    for _ in range(100):
        eng.step()
        if eng._inflight is not None:
            break
    assert eng._inflight is not None
    eng.abort_request("a0")           # longest-running request, mid-flight
    for _ in range(500):
        if not eng.has_work():
            break
        eng.step()
    assert not eng.has_work()
    # Aborted request stopped early; survivors match the sync run.
    assert len(reqs[0].output_token_ids) < 16
    sync = EngineCore(_cfg(False)).generate(_reqs("s"))
    for i in (1, 2, 3, 4):
        assert list(reqs[i].output_token_ids) == sync[f"s{i}"]


def test_async_blocks_released_after_drain():
    """Speculative tail blocks must not leak once everything finishes."""
    eng = EngineCore(_cfg(True))
    eng.generate(_reqs())
    assert eng.scheduler.num_running == 0
    # All blocks reclaimable (evictor-parked prefix blocks count as free).
    assert eng.kv_manager.num_free_blocks == eng.kv_manager.num_blocks - 1


def test_async_off_is_default():
    assert EngineConfig().async_scheduling is False
